"""Host-path vectorization pins: bucket-ladder precompile (no
first-request JIT compile), the zero-object row pipeline
(do_limit_resolved vs do_limit equivalence), the batcher's row ring
copy-before-return contract, and the host-stage histograms the bench's
host_split block reads."""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from api_ratelimit_tpu.backends.batcher import MicroBatcher
from api_ratelimit_tpu.backends.tpu import SlabDeviceEngine, TpuRateLimitCache, _Item
from api_ratelimit_tpu.limiter.base_limiter import BaseRateLimiter
from api_ratelimit_tpu.models import Code, Descriptor, RateLimitRequest, Unit
from api_ratelimit_tpu.stats import Store, TestSink
from api_ratelimit_tpu.utils import FakeTimeSource


class TestPrecompile:
    def test_ladder_fully_covered_and_slab_untouched(self):
        ts = FakeTimeSource(1000)
        eng = SlabDeviceEngine(
            time_source=ts,
            n_slots=1 << 10,
            buckets=(8, 16),
            use_pallas=False,
            precompile=True,
        )
        try:
            assert set(eng.precompiled) == {
                (bucket, dtype)
                for bucket in (8, 16)
                for dtype in ("uint8", "uint16", "uint32")
            }
            # the all-padding warmers must leave the slab bit-empty
            assert int(np.asarray(eng._state.count).sum()) == 0
            assert eng.health_snapshot()["live_slots"] == 0
            # and real traffic starts from a clean counter
            assert eng.submit(
                [_Item(fp=42, hits=1, limit=10, divider=60, jitter=0)]
            ) == [1]
        finally:
            eng.close()

    def test_no_first_request_jit_compile(self):
        """The acceptance pin: after precompile, the first real submit
        must be a jit cache HIT for every readback width the ladder can
        produce."""
        from api_ratelimit_tpu.ops import slab

        ts = FakeTimeSource(1000)
        eng = SlabDeviceEngine(
            time_source=ts,
            n_slots=1 << 10,
            buckets=(8,),
            use_pallas=False,
            precompile=True,
        )
        try:
            size_before = slab.slab_step_after._cache_size()
            # u8, u16, u32 readback widths, all inside bucket 8
            eng.submit([_Item(fp=1, hits=1, limit=10, divider=60, jitter=0)])
            eng.submit([_Item(fp=2, hits=1, limit=1000, divider=60, jitter=0)])
            eng.submit([_Item(fp=3, hits=1, limit=100_000, divider=60, jitter=0)])
            assert slab.slab_step_after._cache_size() == size_before
        finally:
            eng.close()

    def test_runner_precompiles_before_ready(self, tmp_path, monkeypatch):
        """TPU_PRECOMPILE=true: the ladder is compiled by the time the
        runner reports ready/healthy — a first request can never ride a
        compile."""
        from api_ratelimit_tpu.runner import Runner
        from api_ratelimit_tpu.settings import Settings

        config_dir = tmp_path / "current" / "ratelimit" / "config"
        config_dir.mkdir(parents=True)
        (config_dir / "basic.yaml").write_text(
            "domain: basic\n"
            "descriptors:\n"
            "  - key: key1\n"
            "    rate_limit: {unit: second, requests_per_unit: 50}\n"
        )
        settings = Settings(
            port=0,
            grpc_port=0,
            debug_port=0,
            use_statsd=False,
            runtime_path=str(tmp_path / "current"),
            runtime_subdirectory="ratelimit",
            backend_type="tpu",
            tpu_slab_slots=1 << 10,
            tpu_precompile=True,
            tpu_buckets="8",
            tpu_use_pallas=False,
            expiration_jitter_max_seconds=0,
            log_level="ERROR",
        )
        runner = Runner(settings, sink=TestSink())
        runner.run_background()
        try:
            assert runner.wait_ready(30.0)
            engine = runner.service._cache.engine
            assert set(engine.precompiled) == {
                (8, "uint8"), (8, "uint16"), (8, "uint32")
            }
        finally:
            runner.stop()


def _make_pair(local_cache_size=0, jitter_max=0, seed=7):
    """Two independent identical stacks: one driven through
    do_limit_resolved, one through legacy do_limit."""
    import random

    from api_ratelimit_tpu.limiter import LocalCache

    stacks = []
    for _ in range(2):
        ts = FakeTimeSource(1_000_000)
        local = LocalCache(local_cache_size, ts) if local_cache_size else None
        base = BaseRateLimiter(
            ts,
            jitter_rand=random.Random(seed),
            expiration_jitter_max_seconds=jitter_max,
            local_cache=local,
            near_limit_ratio=0.8,
        )
        cache = TpuRateLimitCache(
            base,
            n_slots=1 << 12,
            buckets=(8, 128),
            max_batch=1024,
            use_pallas=False,
        )
        stacks.append((ts, cache))
    return stacks


def _load_cfg(yaml_text):
    from api_ratelimit_tpu.config.loader import ConfigFile, load_config
    from api_ratelimit_tpu.stats.sinks import NullSink
    from api_ratelimit_tpu.stats.store import Store as _Store

    return load_config(
        [ConfigFile(name="config.t", contents=yaml_text)],
        _Store(NullSink()).scope("rl"),
    )


_CFG = """\
domain: d
descriptors:
  - key: api
    rate_limit: {unit: minute, requests_per_unit: 4}
  - key: free
  - key: staged
    rate_limit: {unit: hour, requests_per_unit: 2}
    shadow_mode: true
"""


class TestZeroObjectPipeline:
    @pytest.mark.parametrize("local_cache_size", [0, 256])
    def test_resolved_path_matches_legacy_path(self, local_cache_size):
        """Same request stream through do_limit_resolved and do_limit on
        twin stacks (one config each): identical codes, remaining,
        durations, throttle, and per-rule stats."""
        (ts_a, cache_a), (ts_b, cache_b) = _make_pair(local_cache_size)
        cfg_a, cfg_b = _load_cfg(_CFG), _load_cfg(_CFG)
        reqs = []
        for i in range(40):
            descs = (
                Descriptor.of(("api", f"u{i % 3}")),
                Descriptor.of(("free", "x")),
                Descriptor.of(("nomatch", "y")),
                Descriptor.of(("staged", f"u{i % 2}")),
            )
            reqs.append(RateLimitRequest(domain="d", descriptors=descs, hits_addend=1 + i % 2))
        try:
            for step, request in enumerate(reqs):
                resolved = [
                    cfg_a.compiled.resolve(request.domain, d)
                    for d in request.descriptors
                ]
                limits = [
                    cfg_b.get_limit(request.domain, d)
                    for d in request.descriptors
                ]
                ra = cache_a.do_limit_resolved(request, resolved)
                rb = cache_b.do_limit(request, limits)
                assert ra.throttle_millis == rb.throttle_millis, step
                for i, (sa, sb) in enumerate(
                    zip(ra.descriptor_statuses, rb.descriptor_statuses)
                ):
                    assert sa.code == sb.code, (step, i)
                    assert sa.limit_remaining == sb.limit_remaining, (step, i)
                    assert sa.duration_until_reset == sb.duration_until_reset, (step, i)
                if step % 10 == 9:
                    ts_a.advance(30)
                    ts_b.advance(30)
            for key in ("d.api", "d.staged"):
                la = cfg_a.get_limit("d", Descriptor.of((key.split(".")[1], "u0")))
                lb = cfg_b.get_limit("d", Descriptor.of((key.split(".")[1], "u0")))
                assert la.stats.total_hits.value() == lb.stats.total_hits.value()
                assert la.stats.over_limit.value() == lb.stats.over_limit.value()
                assert la.stats.near_limit.value() == lb.stats.near_limit.value()
                assert la.stats.shadow_mode.value() == lb.stats.shadow_mode.value()
        finally:
            cache_a.close()
            cache_b.close()

    def test_jitter_stream_identical(self):
        """The expiry-jitter RNG must be consumed in the same per-
        descriptor order on both paths (seeded streams stay aligned)."""
        (ts_a, cache_a), (ts_b, cache_b) = _make_pair(jitter_max=300, seed=42)
        cfg_a, cfg_b = _load_cfg(_CFG), _load_cfg(_CFG)
        request = RateLimitRequest(
            domain="d",
            descriptors=(
                Descriptor.of(("api", "u")),
                Descriptor.of(("staged", "u")),
            ),
        )
        try:
            for _ in range(5):
                resolved = [
                    cfg_a.compiled.resolve("d", d) for d in request.descriptors
                ]
                limits = [cfg_b.get_limit("d", d) for d in request.descriptors]
                cache_a.do_limit_resolved(request, resolved)
                cache_b.do_limit(request, limits)
            # aligned RNG streams => identical next draw
            assert cache_a._base.jitter_rand.random() == cache_b._base.jitter_rand.random()
        finally:
            cache_a.close()
            cache_b.close()

    def test_service_uses_fast_path_and_flags_work(self):
        """Through RateLimitService: the resolved path is taken (legacy
        do_limit untouched), and host_fast_path=False pins the legacy
        path — the rollback knob."""
        from api_ratelimit_tpu.service.ratelimit import RateLimitService
        from api_ratelimit_tpu.utils.timeutil import RealTimeSource

        class StaticRuntime:
            def snapshot(self):
                class Snap:
                    def keys(self):
                        return ["config.d"]

                    def get(self, key):
                        return _CFG

                return Snap()

            def add_update_callback(self, cb):
                pass

        for fast in (True, False):
            ts = FakeTimeSource(1_000_000)
            base = BaseRateLimiter(ts, near_limit_ratio=0.8)
            cache = TpuRateLimitCache(
                base, n_slots=1 << 10, buckets=(8,), max_batch=8, use_pallas=False
            )
            calls = {"resolved": 0, "legacy": 0}
            real_resolved = cache.do_limit_resolved
            real_legacy = cache.do_limit
            cache.do_limit_resolved = lambda *a, **k: (
                calls.__setitem__("resolved", calls["resolved"] + 1),
                real_resolved(*a, **k),
            )[1]
            cache.do_limit = lambda *a, **k: (
                calls.__setitem__("legacy", calls["legacy"] + 1),
                real_legacy(*a, **k),
            )[1]
            store = Store(TestSink())
            service = RateLimitService(
                runtime=StaticRuntime(),
                cache=cache,
                stats_scope=store.scope("ratelimit").scope("service"),
                time_source=RealTimeSource(),
                host_fast_path=fast,
            )
            request = RateLimitRequest(
                domain="d", descriptors=(Descriptor.of(("api", "u")),)
            )
            code, statuses, _ = service.should_rate_limit(request)
            assert code == Code.OK
            assert statuses[0].current_limit.requests_per_unit == 4
            if fast:
                assert calls == {"resolved": 1, "legacy": 0}
            else:
                assert calls == {"resolved": 0, "legacy": 1}
            cache.close()

    def test_host_stage_histograms_recorded(self):
        """ratelimit.host.{key_compose_ms,response_ms} and
        ratelimit.service.host.matcher_ms — the sources for the bench's
        host_split block — record once per request."""
        from api_ratelimit_tpu.service.ratelimit import RateLimitService
        from api_ratelimit_tpu.utils.timeutil import RealTimeSource

        class StaticRuntime:
            def snapshot(self):
                class Snap:
                    def keys(self):
                        return ["config.d"]

                    def get(self, key):
                        return _CFG

                return Snap()

            def add_update_callback(self, cb):
                pass

        store = Store(TestSink())
        ts = FakeTimeSource(1_000_000)
        base = BaseRateLimiter(ts, near_limit_ratio=0.8)
        cache = TpuRateLimitCache(
            base,
            n_slots=1 << 10,
            buckets=(8,),
            max_batch=8,
            use_pallas=False,
            stats_scope=store.scope("ratelimit"),
        )
        service = RateLimitService(
            runtime=StaticRuntime(),
            cache=cache,
            stats_scope=store.scope("ratelimit").scope("service"),
            time_source=RealTimeSource(),
        )
        request = RateLimitRequest(
            domain="d", descriptors=(Descriptor.of(("api", "u")),)
        )
        for _ in range(3):
            service.should_rate_limit(request)
        hists = store.metrics_snapshot()["histograms"]
        for name in (
            "ratelimit.host.key_compose_ms",
            "ratelimit.host.response_ms",
            "ratelimit.service.host.matcher_ms",
        ):
            assert hists[name]["count"] == 3, name
        cache.close()


class TestRowRing:
    def test_ring_copies_before_submit_returns(self):
        """The caller may reuse its scratch block the moment submit()
        returns: mutate the submitted block while the batch is gated
        mid-flight — results must reflect the ORIGINAL rows."""
        gate = threading.Event()
        seen = []

        def launch(blocks):
            seen.extend(np.array(b) for b in blocks)
            return [np.array(b) for b in blocks]

        def collect(token):
            gate.wait(5.0)
            return np.concatenate([b[2] for b in token])  # the hits row

        b = MicroBatcher(
            lambda blocks: collect(launch(blocks)),
            window_seconds=0.005,
            max_batch=64,
            execute_launch=launch,
            execute_collect=collect,
            block_mode=True,
            arena_rows=128,
        )
        scratch = np.zeros((6, 2), dtype=np.uint32)
        scratch[2] = (7, 9)
        out = []
        t = threading.Thread(target=lambda: out.append(b.submit(scratch)))
        t.start()
        # wait until the rows are enqueued (copied into the ring), then
        # clobber the caller's scratch before allowing the collect
        deadline = time.monotonic() + 2.0
        while not seen and time.monotonic() < deadline:
            time.sleep(0.002)
        scratch[:] = 0xFFFF
        gate.set()
        t.join(5.0)
        b.close()
        assert out and out[0].tolist() == [7, 9]

    def test_ring_overflow_falls_back_to_owned_copies(self):
        """Blocks past the ring capacity still submit correctly (the
        overflow path copies instead of failing)."""
        b = MicroBatcher(
            lambda blocks: np.concatenate([np.asarray(blk)[2] for blk in blocks]),
            window_seconds=0.002,
            max_batch=4096,
            block_mode=True,
            arena_rows=8,  # tiny ring: most submits overflow
        )
        outs = []
        lock = threading.Lock()

        def one(i):
            block = np.zeros((6, 3), dtype=np.uint32)
            block[2] = (i, i + 100, i + 200)
            got = b.submit(block)
            with lock:
                outs.append((i, list(got)))

        threads = [threading.Thread(target=one, args=(i,)) for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(5.0)
        b.close()
        assert len(outs) == 16
        for i, got in outs:
            assert got == [i, i + 100, i + 200]

    def test_engine_scratch_reuse_is_safe_under_concurrency(self):
        """do_limit_resolved from many threads over the windowed engine:
        each caller's counts are exact (thread-local scratch + ring copy
        never cross-contaminate)."""
        cfg = _load_cfg(
            "domain: d\n"
            "descriptors:\n"
            "  - key: api\n"
            "    rate_limit: {unit: hour, requests_per_unit: 1000000}\n"
        )
        ts = FakeTimeSource(1_000_000)
        base = BaseRateLimiter(ts, near_limit_ratio=0.8)
        cache = TpuRateLimitCache(
            base,
            n_slots=1 << 12,
            batch_window_seconds=0.002,
            buckets=(8, 128),
            max_batch=128,
            use_pallas=False,
        )
        per_thread = 25
        remaining: dict[int, list] = {}

        def worker(tid):
            request = RateLimitRequest(
                domain="d", descriptors=(Descriptor.of(("api", f"u{tid}")),)
            )
            resolved = [cfg.compiled.resolve("d", d) for d in request.descriptors]
            got = []
            for _ in range(per_thread):
                resp = cache.do_limit_resolved(request, resolved)
                got.append(resp.descriptor_statuses[0].limit_remaining)
            remaining[tid] = got

        threads = [
            threading.Thread(target=worker, args=(tid,)) for tid in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(20.0)
        cache.close()
        # per-key counters are disjoint: each thread must see exactly
        # 1M-1, 1M-2, ... in order
        for tid, got in remaining.items():
            assert got == [1_000_000 - i for i in range(1, per_thread + 1)], tid
