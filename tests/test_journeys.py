"""End-to-end journey tracing + the tail-sampled flight recorder.

Covers the PR-7 tentpole: trace context riding the dispatch ring (batch
spans with followsFrom links, per-stage child spans closing the request
span's blind gap), B3 over the sidecar wire (one trace across both
processes, surviving retries/redials and a breaker half-open probe), the
journey recorder's tail sampling, dispatch-arm stage parity, and the
debug-port exports (/debug/journeys, /debug/profile)."""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from api_ratelimit_tpu.backends.sidecar import (
    SidecarEngineClient,
    SlabSidecarServer,
)
from api_ratelimit_tpu.backends.tpu import SlabDeviceEngine
from api_ratelimit_tpu.limiter.cache import CacheError
from api_ratelimit_tpu.tracing import (
    RecordingTracer,
    activate,
    reset_global_tracer,
    set_global_tracer,
)
from api_ratelimit_tpu.tracing import journeys
from api_ratelimit_tpu.tracing.journeys import (
    STAGES,
    JourneyRecorder,
    set_global_recorder,
)
from api_ratelimit_tpu.utils import RealTimeSource


@pytest.fixture(autouse=True)
def _clean_globals():
    reset_global_tracer()
    set_global_recorder(None)
    yield
    reset_global_tracer()
    set_global_recorder(None)


def make_engine(window=0.002, dispatch_loop=True, block_mode=False):
    return SlabDeviceEngine(
        time_source=RealTimeSource(),
        n_slots=1 << 12,
        batch_window_seconds=window,
        max_batch=1024,
        buckets=(8, 64),
        use_pallas=False,
        block_mode=block_mode,
        dispatch_loop=dispatch_loop,
    )


def block(n=2, limit=100):
    out = np.zeros((6, n), dtype=np.uint32)
    out[0] = np.arange(1, n + 1)  # fp_lo
    out[2] = 1  # hits
    out[3] = limit
    out[4] = 60  # divider
    return out


class TestJourneyRecorder:
    def test_begin_mark_finish_and_stage_order(self):
        rec = JourneyRecorder(slow_ms=1e9)
        j = rec.begin("request", trace_id=0xAB, span_id=0xCD)
        assert rec.current() is j
        for stage in STAGES:
            j.mark(stage)
        promoted = rec.finish(j, 1.5)
        assert promoted is False  # no flags, not slow
        assert rec.current() is None
        assert set(j.stages) == set(STAGES)
        assert j.duration_ms == 1.5

    @pytest.mark.parametrize(
        "flag", ["shed", "deadline", "fault", "over_limit"]
    )
    def test_outcome_flags_promote(self, flag):
        rec = JourneyRecorder(slow_ms=1e9)
        j = rec.begin("request")
        assert rec.finish(j, 0.1, flags=(flag,)) is True
        (got,) = rec.retained()
        assert flag in got.flags

    def test_slow_threshold_promotes(self):
        rec = JourneyRecorder(slow_ms=10.0)
        fast = rec.begin("request")
        assert rec.finish(fast, 5.0) is False
        slow = rec.begin("request")
        assert rec.finish(slow, 50.0) is True
        (got,) = rec.retained()
        assert "slow" in got.flags

    def test_live_p99_promotion_when_knob_zero(self):
        rec = JourneyRecorder(slow_ms=0.0)
        # build a baseline of fast journeys so the p99 estimate settles
        for _ in range(256):
            rec.finish(rec.begin("request"), 1.0)
        outlier = rec.begin("request")
        assert rec.finish(outlier, 500.0) is True
        assert any("slow" in j.flags for j in rec.retained())

    def test_note_flag_merges_at_finish(self):
        rec = JourneyRecorder(slow_ms=1e9)
        set_global_recorder(rec)
        j = rec.begin("request")
        journeys.note_flag(journeys.FLAG_SHED)
        rec.finish(j, 0.1)
        (got,) = rec.retained()
        assert "shed" in got.flags

    def test_retained_buffer_bounded(self):
        rec = JourneyRecorder(slow_ms=1e9, retain=4)
        for i in range(10):
            rec.finish(rec.begin("request"), 0.1, flags=("fault",))
        assert len(rec.retained()) == 4

    def test_snapshot_and_json_shape(self):
        rec = JourneyRecorder(slow_ms=1e9)
        j = rec.begin("request", trace_id=7)
        j.mark("publish", 100)
        rec.finish(j, 0.2, flags=("fault",))
        snap = json.loads(rec.dump_json())
        assert snap["enabled"] is True
        (retained,) = snap["retained"]
        assert retained["trace_id"].endswith("7")
        assert retained["stages"]["publish"] == 100
        assert retained["flags"] == ["fault"]
        assert snap["recent"]  # per-thread ring has the journey too

    def test_module_hooks_noop_when_unregistered(self):
        assert journeys.begin_request() is None
        journeys.mark("publish")  # must not raise
        journeys.merge_owner_stages((1, 2, 3, 4, 5))
        journeys.note_flag("fault")
        assert journeys.recording() is False

    def test_junk_config_rejected(self):
        with pytest.raises(ValueError):
            JourneyRecorder(retain=0)
        with pytest.raises(ValueError):
            JourneyRecorder(ring=-1)
        with pytest.raises(ValueError):
            JourneyRecorder(slow_ms=-1.0)


class TestDispatchArmParity:
    """Both dispatch arms (DISPATCH_LOOP on/off) must record the SAME
    journey stage set — the acceptance pin for the tentpole's 'both arms
    produce the same journey stages' contract."""

    def _journey_stages(self, dispatch_loop: bool) -> set:
        rec = JourneyRecorder(slow_ms=1e9)
        set_global_recorder(rec)
        engine = make_engine(window=0.002, dispatch_loop=dispatch_loop)
        try:
            j = rec.begin("request")
            engine.submit_rows(block())
            rec.finish(j, 1.0)
            return set(j.stages)
        finally:
            engine.close()
            set_global_recorder(None)

    def test_stage_sets_identical_across_arms(self):
        loop_stages = self._journey_stages(dispatch_loop=True)
        batcher_stages = self._journey_stages(dispatch_loop=False)
        assert loop_stages == set(STAGES)
        assert batcher_stages == set(STAGES)

    def test_direct_mode_records_full_stage_set(self):
        rec = JourneyRecorder(slow_ms=1e9)
        set_global_recorder(rec)
        engine = make_engine(window=0.0)
        try:
            j = rec.begin("request")
            engine.submit_rows(block())
            assert set(j.stages) == set(STAGES)
        finally:
            engine.close()


class TestConnectedTrace:
    def test_dispatch_loop_yields_one_connected_trace(self):
        """Request span -> ring/pack/launch/redeem child stages -> a
        dispatch.batch span linking the coalesced request (the tentpole
        acceptance shape, in-process arm)."""
        tracer = RecordingTracer()
        set_global_tracer(tracer)
        engine = make_engine(window=0.002, dispatch_loop=True)
        try:
            request_span = tracer.start_span("request")
            with request_span, activate(request_span):
                out = engine.submit_rows(block())
            assert out.shape == (2,)
        finally:
            engine.close()
        spans = {s.operation_name: s for s in tracer.finished_spans()}
        trace_id = request_span.context.trace_id
        for stage in ("ring_wait", "pack", "launch", "redeem"):
            child = spans[f"dispatch.{stage}"]
            assert child.context.trace_id == trace_id
            assert child.parent_id == request_span.context.span_id
        batch = spans["dispatch.batch"]
        assert [c.span_id for c in batch.links] == [
            request_span.context.span_id
        ]
        assert batch.tags["batch_items"] == 2

    def test_batch_span_links_every_coalesced_request(self):
        tracer = RecordingTracer()
        set_global_tracer(tracer)
        engine = make_engine(window=0.01, dispatch_loop=True)
        barrier = threading.Barrier(3)
        span_ids = []
        lock = threading.Lock()

        def caller(i):
            span = tracer.start_span(f"request-{i}")
            with lock:
                span_ids.append(span.context.span_id)
            with span, activate(span):
                barrier.wait()
                engine.submit_rows(block(n=1))

        threads = [
            threading.Thread(target=caller, args=(i,)) for i in range(3)
        ]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(10)
        finally:
            engine.close()
        batches = [
            s
            for s in tracer.finished_spans()
            if s.operation_name == "dispatch.batch"
        ]
        assert batches
        linked = {c.span_id for b in batches for c in b.links}
        assert linked == set(span_ids)

    def test_untraced_requests_build_no_spans(self):
        tracer = RecordingTracer()
        set_global_tracer(tracer)
        engine = make_engine(window=0.002, dispatch_loop=True)
        try:
            engine.submit_rows(block())
        finally:
            engine.close()
        assert tracer.finished_spans() == []


class TestSidecarWireTrace:
    def _stack(self, tmp_path, fault_injector=None, **client_kwargs):
        engine = make_engine(window=0.002, dispatch_loop=True, block_mode=True)
        path = str(tmp_path / "sidecar.sock")
        server = SlabSidecarServer(path, engine)
        client = SidecarEngineClient(
            path, fault_injector=fault_injector, **client_kwargs
        )
        return engine, server, client

    def test_same_trace_id_on_both_sides_of_the_wire(self, tmp_path):
        tracer = RecordingTracer()
        set_global_tracer(tracer)
        engine, server, client = self._stack(tmp_path)
        try:
            request_span = tracer.start_span("request")
            with request_span, activate(request_span):
                out = client.submit_rows(block())
            assert out.shape == (2,)
        finally:
            client.close()
            server.close()
        spans = {s.operation_name: s for s in tracer.finished_spans()}
        trace_id = request_span.context.trace_id
        rpc = spans["sidecar.submit"]  # frontend-process client span
        assert rpc.context.trace_id == trace_id
        assert rpc.parent_id == request_span.context.span_id
        srv = spans["sidecar.submit_rows"]  # device-owner-process span
        assert srv.context.trace_id == trace_id
        assert srv.parent_id == rpc.context.span_id
        # the device-owner batch span links the server-side request span
        batch = spans["dispatch.batch"]
        assert any(c.trace_id == trace_id for c in batch.links)

    def test_b3_survives_retry_and_redial_one_trace(self, tmp_path):
        from api_ratelimit_tpu.testing.faults import FaultInjector

        tracer = RecordingTracer()
        set_global_tracer(tracer)
        injector = FaultInjector()
        # backoff sleep "ends the outage": the first post-redial retry
        # succeeds, so the request survives on one trace with the retry
        # story logged on its rpc span
        engine, server, client = self._stack(
            tmp_path,
            fault_injector=injector,
            retries=2,
            sleep=lambda _s: injector.clear(),
        )
        injector.configure("sidecar.submit:error:1.0")
        try:
            request_span = tracer.start_span("request")
            with request_span, activate(request_span):
                out = client.submit_rows(block())
            assert out.shape == (2,)
        finally:
            client.close()
            server.close()
        spans = {s.operation_name: s for s in tracer.finished_spans()}
        rpc = spans["sidecar.submit"]
        events = [f.get("event") for _, f in rpc.logs]
        assert "sidecar.redial" in events  # pooled conn died -> free redial
        assert "sidecar.retry" in events  # then a budgeted retry
        faults = [f for _, f in rpc.logs if f.get("event") == "fault"]
        assert faults and faults[0]["kind"] == "error"
        assert faults[0]["site"] == "sidecar.submit"
        # one trace end to end despite the failed attempts
        assert (
            spans["sidecar.submit_rows"].context.trace_id
            == request_span.context.trace_id
        )

    def test_b3_survives_breaker_half_open_probe(self, tmp_path):
        from api_ratelimit_tpu.testing.faults import FaultInjector

        tracer = RecordingTracer()
        set_global_tracer(tracer)
        injector = FaultInjector()
        engine, server, client = self._stack(
            tmp_path,
            fault_injector=injector,
            retries=0,
            breaker_threshold=1,
            breaker_reset=0.05,
        )
        try:
            injector.configure("sidecar.submit:error:1.0")
            with pytest.raises(CacheError):
                client.submit_rows(block())
            assert not client.breaker.allow()  # open: failing fast
            injector.clear()
            time.sleep(0.1)  # open -> half-open probe window
            probe_span = tracer.start_span("probe-request")
            with probe_span, activate(probe_span):
                out = client.submit_rows(block())
            assert out.shape == (2,)
        finally:
            client.close()
            server.close()
        srv = [
            s
            for s in tracer.finished_spans()
            if s.operation_name == "sidecar.submit_rows"
        ]
        # the half-open probe request still carried its B3 context
        assert srv and srv[-1].context.trace_id == probe_span.context.trace_id

    def test_sidecar_server_records_journeys(self, tmp_path):
        rec = JourneyRecorder(slow_ms=1e9)
        set_global_recorder(rec)
        engine, server, client = self._stack(tmp_path)
        try:
            client.submit_rows(block())
            deadline = time.monotonic() + 2
            while time.monotonic() < deadline:
                snap = rec.snapshot()
                kinds = [
                    j["kind"]
                    for ring in snap["recent"].values()
                    for j in ring
                ]
                if "sidecar.submit" in kinds:
                    break
                time.sleep(0.01)
            else:
                pytest.fail("sidecar journey never recorded")
        finally:
            client.close()
            server.close()


class TestDispatchTelemetry:
    def test_ring_wait_exemplar_attached_for_traced_slow_frame(self):
        from api_ratelimit_tpu.stats import Store, TestSink

        # one-boundary ladder: every recorded value is "slow" (overflow
        # bucket), so the exemplar path runs deterministically
        store = Store(TestSink(), latency_buckets=(1e-9,))
        tracer = RecordingTracer()
        set_global_tracer(tracer)
        engine = SlabDeviceEngine(
            time_source=RealTimeSource(),
            n_slots=1 << 12,
            batch_window_seconds=0.002,
            buckets=(8, 64),
            use_pallas=False,
            scope=store.scope("ratelimit"),
            dispatch_loop=True,
        )
        try:
            span = tracer.start_span("request")
            with span, activate(span):
                engine.submit_rows(block())
        finally:
            engine.close()
        hists = store.metrics_snapshot()["histograms"]
        want = f"{span.context.trace_id:032x}"
        for name in (
            "ratelimit.dispatch.ring_wait_ms",
            "ratelimit.dispatch.launch_ms",
            "ratelimit.dispatch.redeem_ms",
        ):
            snap = hists[name]
            assert snap["count"] >= 1
            assert snap["exemplar"]["trace_id"] == want, name

    def test_dispatch_launch_fault_logs_kind_on_batch_span(self):
        from api_ratelimit_tpu.testing.faults import FaultInjector

        tracer = RecordingTracer()
        set_global_tracer(tracer)
        injector = FaultInjector()
        engine = SlabDeviceEngine(
            time_source=RealTimeSource(),
            n_slots=1 << 12,
            batch_window_seconds=0.002,
            buckets=(8, 64),
            use_pallas=False,
            fault_injector=injector,
            dispatch_loop=True,
        )
        injector.configure("dispatch.launch:error:1.0")
        try:
            span = tracer.start_span("request")
            with pytest.raises(CacheError):
                with span, activate(span):
                    engine.submit_rows(block())
        finally:
            injector.clear()
            engine.close()
        batches = [
            s
            for s in tracer.finished_spans()
            if s.operation_name == "dispatch.batch"
        ]
        assert batches
        faults = [
            f
            for _, f in batches[0].logs
            if f.get("event") == "fault"
        ]
        assert faults and faults[0]["kind"] == "error"
        assert faults[0]["site"] == "dispatch.launch"
        assert batches[0].tags.get("error") is True


class TestServiceJourneys:
    def _service(self, test_store, cache=None):
        from api_ratelimit_tpu.backends.memory import MemoryRateLimitCache
        from api_ratelimit_tpu.limiter.base_limiter import BaseRateLimiter
        from api_ratelimit_tpu.service.ratelimit import RateLimitService
        from api_ratelimit_tpu.utils.timeutil import FakeTimeSource

        store, _sink = test_store

        class FakeRuntime:
            def snapshot(self):
                class Snap:
                    def keys(self):
                        return ["config.basic"]

                    def get(self, key):
                        return (
                            "domain: basic\n"
                            "descriptors:\n"
                            "  - key: k1\n"
                            "    rate_limit: {unit: minute, requests_per_unit: 2}\n"
                        )

                return Snap()

            def add_update_callback(self, cb):
                pass

        ts = FakeTimeSource(1234)
        base = BaseRateLimiter(time_source=ts, jitter_rand=None)
        return RateLimitService(
            runtime=FakeRuntime(),
            cache=cache or MemoryRateLimitCache(base),
            stats_scope=store.scope("ratelimit").scope("service"),
            time_source=ts,
            runtime_watch_root=True,
        )

    def test_over_limit_journey_promoted(self, test_store):
        from api_ratelimit_tpu.models.descriptors import (
            Descriptor,
            RateLimitRequest,
        )

        rec = JourneyRecorder(slow_ms=1e9)
        set_global_recorder(rec)
        service = self._service(test_store)
        req = RateLimitRequest(
            domain="basic", descriptors=(Descriptor.of(("k1", "v1")),)
        )
        for _ in range(3):
            service.should_rate_limit(req)
        retained = rec.retained()
        assert retained and "over_limit" in retained[-1].flags
        assert retained[-1].kind == "request"

    def test_fault_journey_promoted(self, test_store):
        from api_ratelimit_tpu.models.descriptors import (
            Descriptor,
            RateLimitRequest,
        )

        class BoomCache:
            def do_limit(self, request, limits):
                raise CacheError("backend down")

            def flush(self):
                pass

        rec = JourneyRecorder(slow_ms=1e9)
        set_global_recorder(rec)
        service = self._service(test_store, cache=BoomCache())
        req = RateLimitRequest(
            domain="basic", descriptors=(Descriptor.of(("k1", "v1")),)
        )
        with pytest.raises(CacheError):
            service.should_rate_limit(req)
        (got,) = rec.retained()
        assert "fault" in got.flags

    def test_journey_carries_trace_id_of_active_span(self, test_store):
        from api_ratelimit_tpu.models.descriptors import (
            Descriptor,
            RateLimitRequest,
        )

        rec = JourneyRecorder(slow_ms=1e9)
        set_global_recorder(rec)
        tracer = RecordingTracer()
        set_global_tracer(tracer)
        service = self._service(test_store)
        req = RateLimitRequest(
            domain="basic", descriptors=(Descriptor.of(("k1", "v1")),)
        )
        with tracer.start_span("rpc") as span, activate(span):
            service.should_rate_limit(req)
        snap = rec.snapshot()
        recorded = [j for ring in snap["recent"].values() for j in ring]
        assert recorded
        assert recorded[-1]["trace_id"] == f"{span.context.trace_id:032x}"


class TestDebugEndpoints:
    def _get(self, port, path, timeout=5):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout
        ) as resp:
            return resp.status, resp.read()

    def test_debug_journeys_endpoint(self, test_store):
        from api_ratelimit_tpu.server.http_server import new_debug_server

        store, _ = test_store
        rec = JourneyRecorder(slow_ms=1e9)
        set_global_recorder(rec)
        rec.finish(rec.begin("request", trace_id=9), 0.5, flags=("fault",))
        server = new_debug_server("127.0.0.1", 0, store)
        server.serve_background()
        try:
            status, body = self._get(server.port, "/debug/journeys")
        finally:
            server.shutdown()
        assert status == 200
        doc = json.loads(body)
        assert doc["enabled"] is True
        assert doc["retained"][0]["flags"] == ["fault"]

    def test_debug_journeys_disabled_shape(self, test_store):
        from api_ratelimit_tpu.server.http_server import new_debug_server

        store, _ = test_store
        server = new_debug_server("127.0.0.1", 0, store)
        server.serve_background()
        try:
            status, body = self._get(server.port, "/debug/journeys")
        finally:
            server.shutdown()
        assert status == 200
        assert json.loads(body) == {
            "enabled": False,
            "retained": [],
            "recent": {},
        }

    def test_debug_profile_disabled_without_dir(self, test_store):
        from api_ratelimit_tpu.server.http_server import new_debug_server

        store, _ = test_store
        server = new_debug_server("127.0.0.1", 0, store)
        server.serve_background()
        try:
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                self._get(server.port, "/debug/profile?ms=1")
        finally:
            server.shutdown()
        assert exc_info.value.code == 404

    def test_debug_profile_captures_jax_trace(self, test_store, tmp_path):
        import os

        from api_ratelimit_tpu.server.http_server import new_debug_server

        store, _ = test_store
        profile_dir = str(tmp_path / "profiles")
        os.makedirs(profile_dir)
        server = new_debug_server(
            "127.0.0.1", 0, store, profile_dir=profile_dir
        )
        server.serve_background()
        try:
            # the first trace initializes the profiler backend; generous
            # timeout so a cold CI box never flakes this
            status, body = self._get(
                server.port, "/debug/profile?ms=20", timeout=60
            )
        finally:
            server.shutdown()
        assert status == 200
        doc = json.loads(body)
        assert doc["profile_dir"] == profile_dir
        produced = [
            os.path.join(r, f)
            for r, _, fs in os.walk(profile_dir)
            for f in fs
        ]
        assert produced, "profiler wrote no trace files"
