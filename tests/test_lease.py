"""Hierarchical quota leasing (backends/lease.py): the two-tier limiter.

Covers the reservation contract end to end: grant riders through the real
engine, frontend-local decisions byte-identical to the device path
(LEASE_ENABLED=false rollback arm), adaptive sizing (grow on exhaustion-
renewal, shrink on unused expiry, shrink-toward-1 near the limit), the
wire codec + sidecar trailer, the lease-liability snapshot section with
boot-time reconcile + counter floors, and the differential-oracle
overshoot bound: total admitted <= limit + Σ(outstanding lease budgets)
with a device-owner restart mid-stream — and total admitted <= limit when
the liability section restores (a restart never double-grants).
"""

from __future__ import annotations

import random
import threading
from types import SimpleNamespace

import numpy as np
import pytest

from api_ratelimit_tpu.backends.lease import (
    LEASE_ROW_WIDTH,
    LeaseOps,
    LeaseRegistry,
    LeaseTable,
    decode_lease_ops,
    encode_lease_ops,
)
from api_ratelimit_tpu.backends.tpu import SlabDeviceEngine, TpuRateLimitCache
from api_ratelimit_tpu.limiter.base_limiter import BaseRateLimiter
from api_ratelimit_tpu.limiter.local_cache import LocalCache
from api_ratelimit_tpu.models import Code, Descriptor, RateLimitRequest
from api_ratelimit_tpu.service import RateLimitService
from api_ratelimit_tpu.stats import Store, TestSink
from api_ratelimit_tpu.utils import FakeTimeSource

LEASE_YAML = """\
domain: lease
descriptors:
  - key: api_key
    rate_limit: {unit: minute, requests_per_unit: 100}
  - key: open
    rate_limit: {unit: minute, requests_per_unit: 1000000}
"""


class _StaticRuntime:
    def __init__(self, text):
        self._t = text

    def snapshot(self):
        text = self._t

        class Snap:
            def keys(self):
                return ["config.lease"]

            def get(self, key):
                return text

        return Snap()

    def add_update_callback(self, cb):
        pass


def _engine(ts, n_slots=1 << 10):
    return SlabDeviceEngine(
        time_source=ts,
        n_slots=n_slots,
        use_pallas=False,
        buckets=(128,),
        batch_window_seconds=0.0,
    )


def _stack(
    ts,
    lease=True,
    store=None,
    local_cache=None,
    engine=None,
    lease_table=None,
    yaml_text=LEASE_YAML,
    **lease_kw,
):
    """(service, cache, lease_table, store) — direct-mode engine, fake
    clock, deterministic jitter."""
    if store is None:
        store = Store(TestSink())
    base = BaseRateLimiter(
        time_source=ts,
        jitter_rand=random.Random(0),
        expiration_jitter_max_seconds=0,
        local_cache=local_cache,
    )
    if lease and lease_table is None:
        lease_kw.setdefault("min_size", 4)
        lease_kw.setdefault("max_size", 64)
        lease_table = LeaseTable(
            base, scope=store.scope("ratelimit").scope("lease"), **lease_kw
        )
    if engine is None:
        engine = _engine(ts)
    cache = TpuRateLimitCache(base, engine=engine, lease_table=lease_table)
    service = RateLimitService(
        runtime=_StaticRuntime(yaml_text),
        cache=cache,
        stats_scope=store.scope("ratelimit").scope("service"),
        time_source=ts,
        lease=lease_table,
    )
    return service, cache, lease_table, store


def _req(value="hot", key="api_key", hits=1):
    return RateLimitRequest(
        domain="lease",
        descriptors=(Descriptor.of((key, value)),),
        hits_addend=hits,
    )


def _rec(fp=7, divider=60, limit=100):
    """A minimal ResolvedLimit stand-in for plan/register unit tests."""
    return SimpleNamespace(fp=fp, divider=divider, requests_per_unit=limit)


class TestWireCodec:
    def test_round_trip(self):
        ops = LeaseOps(
            grants=[(0, 8, 1_000_020, 15), (3, 64, 1_000_020, 15)],
            settles=[((123 << 32) | 456, 1_000_020, 7)],
        )
        raw = encode_lease_ops(ops)
        # length-prefixed trailer: the framing layer strips the prefix
        (length,) = np.frombuffer(raw[:4], dtype="<u4")
        assert int(length) == len(raw) - 4
        decoded = decode_lease_ops(raw[4:])
        assert decoded.grants == ops.grants
        assert decoded.settles == ops.settles

    def test_empty_ops(self):
        decoded = decode_lease_ops(encode_lease_ops(LeaseOps((), ()))[4:])
        assert decoded.grants == [] and decoded.settles == []

    def test_malformed_body_raises(self):
        with pytest.raises(ValueError):
            decode_lease_ops(b"\x01")
        raw = encode_lease_ops(LeaseOps([(0, 8, 1, 1)], ()))[4:]
        with pytest.raises(ValueError):
            decode_lease_ops(raw[:-4])  # counts disagree with body length


class TestLeaseTableUnit:
    def _table(self, ts=None, **kw):
        ts = ts or FakeTimeSource(1_000_000 - (1_000_000 % 60))
        base = BaseRateLimiter(ts, expiration_jitter_max_seconds=0)
        kw.setdefault("min_size", 4)
        kw.setdefault("max_size", 64)
        return LeaseTable(base, **kw), ts

    def test_junk_params_rejected(self):
        base = BaseRateLimiter(FakeTimeSource(0))
        with pytest.raises(ValueError, match="LEASE_MIN"):
            LeaseTable(base, min_size=0)
        with pytest.raises(ValueError, match="LEASE_MAX"):
            LeaseTable(base, min_size=8, max_size=4)
        with pytest.raises(ValueError, match="LEASE_TTL_FRACTION"):
            LeaseTable(base, ttl_fraction=0.0)
        with pytest.raises(ValueError, match="LEASE_NEAR_LIMIT_RATIO"):
            LeaseTable(base, near_limit_ratio=1.5)

    def test_grant_grows_on_exhaustion_renewal(self):
        table, ts = self._table()
        now = ts.unix_now()
        rec = _rec()
        p1 = table.plan_grant(rec, 1, now)
        assert p1.size == 4
        table.register_grant(p1, after_total=5)  # caller used 1, lease 4
        # exhaust the lease, then the renewal grant doubles
        lease = table._leases[(rec.fp, p1.window)]
        lease.consumed = lease.granted
        p2 = table.plan_grant(rec, 1, now)
        assert p2.size == 8

    def test_ttl_expiry_shrinks_mostly_unused(self):
        table, ts = self._table()
        rec = _rec()
        p1 = table.plan_grant(rec, 1, ts.unix_now())
        table.register_grant(p1, after_total=5)
        # grow the remembered size first
        table._sizes[rec.fp] = 32
        ts.advance(16)  # past the 15s TTL (60s window * 0.25)
        p2 = table.plan_grant(rec, 1, ts.unix_now())
        # the expired lease was 4 tokens, 0 consumed -> halve toward MIN
        assert table._sizes[rec.fp] == max(4, p1.size // 2)
        assert p2 is not None

    def test_lease_never_crosses_window_boundary(self):
        table, ts = self._table()
        window = ts.unix_now() - (ts.unix_now() % 60)
        ts.now = window + 55  # 5s left in the window
        planned = table.plan_grant(_rec(), 1, ts.unix_now())
        assert planned.expires_at == window + 60

    def test_near_limit_shrinks_toward_one(self):
        table, ts = self._table()
        now = ts.unix_now()
        rec = _rec(limit=100)
        window = (now // 60) * 60
        table._after_hint[rec.fp] = (window, 95)  # past 0.9 * 100
        planned = table.plan_grant(rec, 1, now)
        assert planned.size == 2  # headroom 5 // 2
        table.abort_grant(planned)  # release the in-flight mark
        table._after_hint[rec.fp] = (window, 99)
        planned = table.plan_grant(rec, 1, now)
        assert planned.size == 1
        table.abort_grant(planned)
        table._after_hint[rec.fp] = (window, 100)  # no headroom: no lease
        assert table.plan_grant(rec, 1, now) is None

    def test_inflight_guard_blocks_concurrent_riders(self):
        table, ts = self._table()
        now = ts.unix_now()
        planned = table.plan_grant(_rec(), 1, now)
        assert planned is not None
        # a second miss for the same key while the rider is out: no rider
        assert table.plan_grant(_rec(), 1, now) is None
        table.register_grant(planned, after_total=5)
        # a different key is unaffected
        assert table.plan_grant(_rec(fp=8), 1, now) is not None

    def test_degraded_probe_is_sticky_until_success(self):
        table, _ = self._table()
        assert table.degraded_reason() is None
        table.note_device_failure(RuntimeError("sidecar dark"))
        reason = table.degraded_reason()
        assert reason is not None and "lease.degraded" in reason
        table.note_device_failure(RuntimeError("still dark"))
        assert table.degraded
        table.note_success()
        assert table.degraded_reason() is None

    def test_settles_queue_and_requeue(self):
        table, ts = self._table()
        rec = _rec()
        planned = table.plan_grant(rec, 1, ts.unix_now())
        table.register_grant(planned, after_total=5)
        lease = table._leases[(rec.fp, planned.window)]
        lease.consumed = 2
        ts.advance(16)  # expire
        assert table.plan_grant(rec, 1, ts.unix_now()) is not None
        settles = table.drain_settles()
        assert settles == [(rec.fp, planned.window, 2)]
        assert table.drain_settles() == []
        table.requeue_settles(settles)
        assert table.drain_settles() == settles


class TestServiceLeaseLocal:
    def test_byte_identical_to_lease_off_arm(self):
        """The LEASE_ENABLED=false rollback pin: a sequential stream makes
        the SAME decisions leased and unleased — reservation leasing is an
        exact continuation of the device counter (same discipline as the
        HOST_FAST_PATH / DISPATCH_LOOP rollback arms)."""
        ts_on, ts_off = FakeTimeSource(1_000_000), FakeTimeSource(1_000_000)
        svc_on, cache_on, _, _ = _stack(ts_on, lease=True)
        svc_off, cache_off, _, _ = _stack(ts_off, lease=False)
        try:
            for i in range(130):  # crosses the 100/minute limit
                code_on, st_on, _ = svc_on.should_rate_limit(_req())
                code_off, st_off, _ = svc_off.should_rate_limit(_req())
                a, b = st_on[0], st_off[0]
                assert code_on == code_off, i
                assert (
                    a.code,
                    a.limit_remaining,
                    a.duration_until_reset,
                    a.current_limit,
                ) == (
                    b.code,
                    b.limit_remaining,
                    b.duration_until_reset,
                    b.current_limit,
                ), i
                if i % 40 == 0:
                    ts_on.advance(1)
                    ts_off.advance(1)
        finally:
            cache_on.close()
            cache_off.close()

    def test_hot_key_is_answered_frontend_locally(self):
        ts = FakeTimeSource(1_000_000)
        svc, cache, table, store = _stack(ts)
        try:
            for _ in range(50):
                code, _, _ = svc.should_rate_limit(_req(key="open"))
                assert code == Code.OK
            # grants ride the device; everything else answers locally
            device = cache.engine._decisions_total
            assert device < 10, device
            snap = store.debug_snapshot()
            assert snap["ratelimit.lease.local_hits"] == 50 - device
            assert snap["ratelimit.lease.decisions_seen"] == 50
            assert snap["ratelimit.lease.grants"] == device
            # the device-owner registry carries the matching liability
            entries, tokens = cache.engine.lease_registry.outstanding()
            assert entries == 1 and tokens > 0
            held, held_tokens = table.outstanding()
            assert held == 1 and held_tokens > 0
        finally:
            cache.close()

    def test_over_limit_lands_in_local_cache_not_lease(self):
        """Once a key crosses its limit the over-limit cache answers it —
        inside the lease decide path, still device-free — and no further
        budget is granted for it."""
        ts = FakeTimeSource(1_000_000)
        local_cache = LocalCache(max_entries=128, time_source=ts)
        svc, cache, _, store = _stack(ts, local_cache=local_cache)
        try:
            codes = [svc.should_rate_limit(_req())[0] for _ in range(120)]
            assert codes[-1] == Code.OVER_LIMIT
            assert sum(1 for c in codes if c == Code.OK) == 100
            device_at_over = cache.engine._decisions_total
            for _ in range(20):
                code, _, _ = svc.should_rate_limit(_req())
                assert code == Code.OVER_LIMIT
            # the tail was served by the over-limit cache: no device calls
            assert cache.engine._decisions_total == device_at_over
            assert store.debug_snapshot()["ratelimit.lease.cache_hits"] >= 20
        finally:
            cache.close()

    def test_multi_descriptor_partial_miss_rides_device(self):
        """A request mixing a leased and an unleased descriptor goes to the
        device whole — the leased descriptor's budget is NOT consumed (no
        torn half-local answers)."""
        ts = FakeTimeSource(1_000_000)
        svc, cache, table, _ = _stack(ts)
        try:
            svc.should_rate_limit(_req(value="a", key="open"))  # grant "a"
            held_before = table.outstanding()[1]
            request = RateLimitRequest(
                domain="lease",
                descriptors=(
                    Descriptor.of(("open", "a")),
                    Descriptor.of(("open", "brand-new")),
                ),
            )
            code, statuses, _ = svc.should_rate_limit(request)
            assert code == Code.OK and len(statuses) == 2
            # "a"'s lease budget untouched by the device-ridden request
            assert table.outstanding()[1] >= held_before
        finally:
            cache.close()

    def test_journey_marks_lease_local_stage(self):
        from api_ratelimit_tpu.tracing import journeys

        ts = FakeTimeSource(1_000_000)
        svc, cache, _, _ = _stack(ts)
        recorder = journeys.JourneyRecorder(slow_ms=1e9)
        journeys.set_global_recorder(recorder)
        try:
            svc.should_rate_limit(_req(key="open"))  # grant: device path
            svc.should_rate_limit(_req(key="open"))  # leased: local
            snap = recorder.snapshot()
            recent = [
                j
                for ring in snap["recent"].values()
                for j in ring
                if j["kind"] == "request"
            ]
            assert any(
                journeys.STAGE_LEASE_LOCAL in j["stages"] for j in recent
            )
        finally:
            journeys.set_global_recorder(None)
            cache.close()

    def test_concurrent_hot_key_never_over_admits(self):
        """Reservation exactness under concurrency: OK decisions for one
        key never exceed its limit, leases or not."""
        ts = FakeTimeSource(1_000_000)
        svc, cache, _, _ = _stack(ts)
        ok = []
        lock = threading.Lock()

        def worker():
            mine = 0
            for _ in range(60):
                code, _, _ = svc.should_rate_limit(_req())
                if code == Code.OK:
                    mine += 1
            with lock:
                ok.append(mine)

        try:
            threads = [threading.Thread(target=worker) for _ in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert sum(ok) <= 100  # the 100/minute rule
            assert sum(ok) >= 90  # and leasing didn't burn the window away
        finally:
            cache.close()


class TestSidecarLeaseWire:
    def test_grant_and_settle_ride_the_wire(self):
        from api_ratelimit_tpu.backends.sidecar import (
            SidecarEngineClient,
            SlabSidecarServer,
        )

        ts = FakeTimeSource(1_000_000)
        engine = SlabDeviceEngine(
            time_source=ts,
            n_slots=1 << 10,
            use_pallas=False,
            buckets=(128,),
            block_mode=True,
        )
        server = SlabSidecarServer("tcp://127.0.0.1:0", engine)
        try:
            client = SidecarEngineClient(
                f"tcp://127.0.0.1:{server.port}", breaker_threshold=0
            )
            block = np.zeros((6, 1), dtype=np.uint32)
            block[0, 0] = 99  # fp_lo
            block[2, 0] = 1 + 8  # hits + lease rider
            block[3, 0] = 1000  # limit
            block[4, 0] = 60  # divider
            window = (ts.unix_now() // 60) * 60
            afters = client.submit_rows(
                block,
                lease_ops=LeaseOps(
                    grants=[(0, 8, window, 15)], settles=()
                ),
            )
            assert int(afters[0]) == 9
            entries, tokens = engine.lease_registry.outstanding()
            assert (entries, tokens) == (1, 8)
            # settle closes the liability
            client.submit_rows(
                np.array(
                    [[99], [0], [1], [1000], [60], [0]], dtype=np.uint32
                ),
                lease_ops=LeaseOps(
                    grants=(), settles=[(99, window, 8)]
                ),
            )
            assert engine.lease_registry.outstanding() == (0, 0)
            client.close()
        finally:
            server.close()

    def test_sidecar_backed_service_offloads_via_leases(self):
        from api_ratelimit_tpu.backends.sidecar import (
            SidecarEngineClient,
            SlabSidecarServer,
        )

        ts = FakeTimeSource(1_000_000)
        owner = SlabDeviceEngine(
            time_source=ts,
            n_slots=1 << 10,
            use_pallas=False,
            buckets=(128,),
            block_mode=True,
        )
        server = SlabSidecarServer("tcp://127.0.0.1:0", owner)
        try:
            client = SidecarEngineClient(
                f"tcp://127.0.0.1:{server.port}", breaker_threshold=0
            )
            svc, cache, _, store = _stack(ts, engine=client)
            for _ in range(40):
                assert svc.should_rate_limit(_req(key="open"))[0] == Code.OK
            snap = store.debug_snapshot()
            assert snap["ratelimit.lease.local_hits"] >= 30
            # the OWNER process's registry tracks the liability
            entries, tokens = owner.lease_registry.outstanding()
            assert entries == 1 and tokens > 0
            client.close()
        finally:
            server.close()


class TestRegistrySnapshot:
    def test_row_layout_matches_persist_mirror(self):
        from api_ratelimit_tpu.backends import lease as lease_mod
        from api_ratelimit_tpu.persist import snapshot as snap_mod

        assert lease_mod.LEASE_ROW_WIDTH == snap_mod.LEASE_ROW_WIDTH
        for name in (
            "LEASE_COL_FP_LO",
            "LEASE_COL_FP_HI",
            "LEASE_COL_WINDOW",
            "LEASE_COL_GRANTED",
            "LEASE_COL_SETTLED",
            "LEASE_COL_FLOOR",
            "LEASE_COL_EXPIRE",
        ):
            assert getattr(lease_mod, name) == getattr(snap_mod, name), name

    def test_export_import_round_trip(self):
        ts = FakeTimeSource(1_000_000)
        registry = LeaseRegistry(ts)
        registry.grant(7, 999_960, 8, expires_at=1_000_015, floor=9)
        registry.grant(7, 999_960, 16, expires_at=1_000_020, floor=25)
        registry.settle(7, 999_960, 8)
        rows = registry.export_rows()
        assert rows.shape == (1, LEASE_ROW_WIDTH)
        other = LeaseRegistry(ts)
        assert other.import_rows(rows) == 1
        assert other.outstanding() == (1, 16)

    def test_ttl_sweep_drops_dead_liabilities(self):
        ts = FakeTimeSource(1_000_000)
        registry = LeaseRegistry(ts)
        registry.grant(7, 999_960, 8, expires_at=1_000_010, floor=9)
        ts.advance(11)
        assert registry.outstanding() == (0, 0)
        assert registry.export_rows().shape == (0, LEASE_ROW_WIDTH)

    def test_reconcile_and_floors(self):
        from api_ratelimit_tpu.persist.snapshot import (
            COL_COUNT,
            apply_lease_floors,
            reconcile_leases,
        )

        now = 1_000_000
        rows = np.zeros((3, LEASE_ROW_WIDTH), dtype=np.uint32)
        rows[0] = (7, 0, 999_960, 8, 0, 20, now + 10, 0)  # live
        rows[1] = (8, 0, 999_960, 8, 0, 30, now - 1, 0)  # TTL-dead
        rows[2] = (9, 0, 999_960, 8, 8, 40, now + 10, 0)  # fully settled
        kept, stats = reconcile_leases(rows, now)
        assert stats == {"restored": 1, "dropped": 2}
        # slab table: fp 7's counter restored LOWER than the grant floor
        slab = np.zeros((4, 8), dtype=np.uint32)
        slab[2] = (7, 0, 5, 999_960, now + 100, 60, 0, 0)
        floored, unmatched = apply_lease_floors([slab], kept)
        assert (floored, unmatched) == (1, 0)
        assert slab[2, COL_COUNT] == 20

    def test_snapshotter_writes_and_restores_lease_section(self, tmp_path):
        from api_ratelimit_tpu.persist.snapshotter import (
            SlabSnapshotter,
            lease_snapshot_path,
        )

        ts = FakeTimeSource(1_000_000)
        engine = _engine(ts)
        engine.lease_registry.grant(
            7, 999_960, 8, expires_at=1_000_015, floor=9
        )
        engine.lease_registry.grant(
            8, 999_960, 4, expires_at=1_000_002, floor=4
        )
        store = Store(TestSink())
        snap = SlabSnapshotter(
            engine,
            str(tmp_path),
            interval_ms=60_000.0,
            time_source=ts,
            scope=store.scope("ratelimit"),
        )
        assert snap.snapshot_once() > 0
        assert (tmp_path / "leases.snap").exists()
        assert lease_snapshot_path(str(tmp_path)) == str(
            tmp_path / "leases.snap"
        )

        # restore into a fresh engine a few seconds later: fp 8's lease is
        # TTL-dead and must drop (snapshot.restore_dropped_leases)
        ts2 = FakeTimeSource(1_000_005)
        engine2 = _engine(ts2)
        store2 = Store(TestSink())
        snap2 = SlabSnapshotter(
            engine2,
            str(tmp_path),
            interval_ms=60_000.0,
            time_source=ts2,
            scope=store2.scope("ratelimit"),
        )
        stats = snap2.restore()
        assert stats["restored_leases"] == 1
        assert stats["dropped_leases"] == 1
        assert engine2.lease_registry.outstanding() == (1, 8)
        snapshot = store2.debug_snapshot()
        assert snapshot["ratelimit.snapshot.restore_dropped_leases"] == 1
        assert snapshot["ratelimit.snapshot.restore_leases"] == 1

    def test_corrupt_lease_file_degrades_to_slab_only(self, tmp_path):
        from api_ratelimit_tpu.persist.snapshotter import SlabSnapshotter

        ts = FakeTimeSource(1_000_000)
        engine = _engine(ts)
        engine.lease_registry.grant(
            7, 999_960, 8, expires_at=1_000_015, floor=9
        )
        snap = SlabSnapshotter(
            engine, str(tmp_path), interval_ms=60_000.0, time_source=ts
        )
        snap.snapshot_once()
        lease_file = tmp_path / "leases.snap"
        lease_file.write_bytes(lease_file.read_bytes()[:-2] + b"xx")

        engine2 = _engine(ts)
        snap2 = SlabSnapshotter(
            engine2, str(tmp_path), interval_ms=60_000.0, time_source=ts
        )
        stats = snap2.restore()
        # the slab still restores; the lease section is rejected
        assert "reason" not in stats
        assert stats["restored_leases"] == 0
        assert snap2.load_rejected_total == 1
        assert engine2.lease_registry.outstanding() == (0, 0)

    def test_inspect_tool_renders_lease_section(self, tmp_path):
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "snapshot_inspect",
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "tools",
                "snapshot_inspect.py",
            ),
        )
        snapshot_inspect = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(snapshot_inspect)

        from api_ratelimit_tpu.persist.snapshotter import SlabSnapshotter

        ts = FakeTimeSource(1_000_000)
        engine = _engine(ts)
        engine.lease_registry.grant(
            7, 999_960, 8, expires_at=1_000_015, floor=9
        )
        engine.lease_registry.settle(7, 999_960, 3)
        SlabSnapshotter(
            engine, str(tmp_path), interval_ms=60_000.0, time_source=ts
        ).snapshot_once()
        report = snapshot_inspect.inspect_file(
            str(tmp_path / "leases.snap"), now=1_000_000
        )
        assert report["kind"] == "leases"
        leases = report["leases"]
        assert leases["outstanding"] == 1
        assert leases["granted_tokens"] == 8
        assert leases["settled_tokens"] == 3
        assert leases["unsettled_tokens"] == 5
        assert leases["restorable"] == 1
        # the CLI accepts a mixed file set and exits 0
        rc = snapshot_inspect.main(
            [
                str(tmp_path / "slab.snap"),
                str(tmp_path / "leases.snap"),
                "--json",
                "--now",
                "1000000",
            ]
        )
        assert rc == 0


class TestOvershootBound:
    """The differential-oracle acceptance pin: under concurrent traffic,
    lease expiry, and a device-owner restart mid-stream, total admitted
    <= limit + Σ(outstanding lease budgets at the crash) — and with the
    liability section restored, total admitted <= limit exactly (a
    restart never double-grants)."""

    LIMIT = 100

    def _drive(self, svc, n, threads=3):
        ok = []
        lock = threading.Lock()

        def worker():
            mine = 0
            for _ in range(n):
                code, _, _ = svc.should_rate_limit(_req())
                if code == Code.OK:
                    mine += 1
            with lock:
                ok.append(mine)

        ts_threads = [
            threading.Thread(target=worker) for _ in range(threads)
        ]
        for t in ts_threads:
            t.start()
        for t in ts_threads:
            t.join()
        return sum(ok)

    def _crash_restart(self, tmp_path, restore_leases: bool):
        from api_ratelimit_tpu.persist.snapshotter import SlabSnapshotter

        ts = FakeTimeSource(1_000_000)
        store = Store(TestSink())
        base = BaseRateLimiter(
            ts, jitter_rand=random.Random(0), expiration_jitter_max_seconds=0
        )
        table = LeaseTable(base, min_size=4, max_size=32)
        engine1 = _engine(ts)
        cache1 = TpuRateLimitCache(base, engine=engine1, lease_table=table)
        svc1 = RateLimitService(
            runtime=_StaticRuntime(LEASE_YAML),
            cache=cache1,
            stats_scope=store.scope("ratelimit").scope("service"),
            time_source=ts,
            lease=table,
        )
        admitted = self._drive(svc1, 12)  # ~36 decisions, leases warm
        snapper = SlabSnapshotter(
            engine1, str(tmp_path), interval_ms=60_000.0, time_source=ts
        )
        snapper.snapshot_once()
        # outstanding budgets at the crash: what frontends may still admit
        # locally, and what an un-floored restart would re-admit
        _, outstanding = table.outstanding()
        _, registry_outstanding = engine1.lease_registry.outstanding()
        cache1.close()

        if not restore_leases:
            (tmp_path / "leases.snap").unlink()

        # the device owner restarts; the frontend (lease table) survives
        engine2 = _engine(ts)
        SlabSnapshotter(
            engine2, str(tmp_path), interval_ms=60_000.0, time_source=ts
        ).restore()
        cache2 = TpuRateLimitCache(base, engine=engine2, lease_table=table)
        svc2 = RateLimitService(
            runtime=_StaticRuntime(LEASE_YAML),
            cache=cache2,
            stats_scope=Store(TestSink()).scope("ratelimit").scope("service"),
            time_source=ts,
            lease=table,
        )
        # run well past the limit, including a lease-expiry boundary
        admitted += self._drive(svc2, 25)
        ts.advance(16)  # expire outstanding leases mid-stream
        admitted += self._drive(svc2, 15)
        cache2.close()
        return admitted, outstanding, registry_outstanding

    def test_liability_restore_never_double_grants(self, tmp_path):
        admitted, _, _ = self._crash_restart(tmp_path, restore_leases=True)
        assert admitted <= self.LIMIT

    def test_overshoot_without_liabilities_bounded_by_budgets(
        self, tmp_path
    ):
        admitted, outstanding, registry_outstanding = self._crash_restart(
            tmp_path, restore_leases=False
        )
        # the bound is the REGISTRY's view at the snapshot: granted minus
        # settled; the frontend's own outstanding is a subset of it
        assert outstanding <= registry_outstanding
        assert admitted <= self.LIMIT + registry_outstanding


class TestRunnerIntegration:
    """LEASE_ENABLED wiring end to end: the runner builds the lease table,
    hot keys answer locally, the degraded probe is on the health surface,
    and the default (disabled) boot wires nothing."""

    BASIC = (
        "domain: lease\n"
        "descriptors:\n"
        "  - key: api_key\n"
        "    rate_limit: {unit: hour, requests_per_unit: 1000000}\n"
    )

    def _settings(self, tmp_path, **kw):
        from api_ratelimit_tpu.settings import Settings

        config_dir = tmp_path / "current" / "rl" / "config"
        if not config_dir.exists():
            config_dir.mkdir(parents=True)
            (config_dir / "lease.yaml").write_text(self.BASIC)
        return Settings(
            port=0,
            grpc_port=0,
            debug_port=0,
            use_statsd=False,
            runtime_path=str(tmp_path / "current"),
            runtime_subdirectory="rl",
            backend_type="tpu",
            tpu_slab_slots=1 << 10,
            tpu_use_pallas=False,
            expiration_jitter_max_seconds=0,
            log_level="ERROR",
            **kw,
        )

    def test_disabled_by_default(self, tmp_path):
        from api_ratelimit_tpu.runner import Runner

        runner = Runner(self._settings(tmp_path), sink=TestSink())
        runner.run_background()
        assert runner.wait_ready(10.0)
        try:
            assert runner.lease_table is None
        finally:
            runner.stop()

    def test_enabled_serves_locally_and_probes_health(self, tmp_path):
        from api_ratelimit_tpu.runner import Runner

        runner = Runner(
            self._settings(tmp_path, lease_enabled=True, lease_min=4),
            sink=TestSink(),
        )
        runner.run_background()
        assert runner.wait_ready(10.0)
        try:
            assert runner.lease_table is not None
            for _ in range(20):
                code, _, _ = runner.service.should_rate_limit(_req())
                assert code == Code.OK
            held, tokens = runner.lease_table.outstanding()
            assert held == 1 and tokens > 0
            engine = runner.service._cache.engine
            assert engine.lease_registry.outstanding()[0] == 1
            # the degraded probe is wired into /healthcheck
            runner.lease_table.note_device_failure(RuntimeError("dark"))
            assert any(
                "lease.degraded" in r
                for r in runner.server.health.degraded_reasons()
            )
            runner.lease_table.note_success()
            assert runner.server.health.degraded_reasons() == []
        finally:
            runner.stop()


class TestDispatchLoopArm:
    def test_leases_ride_the_dispatch_loop(self):
        """Windowed mode (DISPATCH_LOOP): grant riders travel the submit
        rings like any other frame and the liability registers from the
        ticket's verdicts."""
        ts = FakeTimeSource(1_000_000)
        engine = SlabDeviceEngine(
            time_source=ts,
            n_slots=1 << 10,
            use_pallas=False,
            buckets=(128,),
            batch_window_seconds=0.0002,
            dispatch_loop=True,
        )
        svc, cache, table, store = _stack(ts, engine=engine)
        try:
            for _ in range(40):
                assert svc.should_rate_limit(_req(key="open"))[0] == Code.OK
            snap = store.debug_snapshot()
            assert snap["ratelimit.lease.local_hits"] >= 30
            assert engine.lease_registry.outstanding()[0] == 1
        finally:
            cache.close()


# ---------------------------------------------------------------------------
# Leases x warm-standby failover (persist/replication.py): grants made by
# the old primary stay locally servable through a promotion, liabilities
# replicate so the promoted standby's floors prevent double-granting,
# settles land against the new epoch, and lease.degraded clears once the
# standby is serving.
# ---------------------------------------------------------------------------

FAILOVER_YAML = """\
domain: lease
descriptors:
  - key: api_key
    rate_limit: {unit: hour, requests_per_unit: 50}
"""


class TestLeaseAcrossFailover:
    INTERVAL_MS = 20.0

    def _owner(self, sock, role, peer=None, start_server=True):
        from api_ratelimit_tpu.backends.sidecar import SlabSidecarServer
        from api_ratelimit_tpu.persist.replication import (
            ReplicationCoordinator,
        )
        from api_ratelimit_tpu.utils.timeutil import RealTimeSource

        engine = SlabDeviceEngine(
            time_source=RealTimeSource(),
            n_slots=1 << 10,
            use_pallas=False,
            buckets=(128,),
            block_mode=True,
        )
        coord = ReplicationCoordinator(
            engine,
            role,
            peer_address=peer,
            interval_ms=self.INTERVAL_MS,
        )
        server = (
            SlabSidecarServer(sock, engine, repl=coord)
            if start_server
            else None
        )
        coord.start()
        return engine, coord, server

    def _frontend(self, addrs, **client_kw):
        import time as time_mod

        from api_ratelimit_tpu.backends.sidecar import SidecarEngineClient
        from api_ratelimit_tpu.utils.timeutil import RealTimeSource

        client_kw.setdefault("retries", 2)
        client_kw.setdefault("retry_backoff", 0.002)
        client_kw.setdefault("retry_backoff_max", 0.02)
        client_kw.setdefault("breaker_threshold", 2)
        client_kw.setdefault("breaker_reset", 0.05)
        client = SidecarEngineClient(addrs, **client_kw)
        store = Store(TestSink())
        base = BaseRateLimiter(
            time_source=RealTimeSource(),
            jitter_rand=random.Random(0),
            expiration_jitter_max_seconds=0,
        )
        table = LeaseTable(
            base,
            min_size=4,
            max_size=16,
            scope=store.scope("ratelimit").scope("lease"),
        )
        cache = TpuRateLimitCache(base, engine=client, lease_table=table)
        svc = RateLimitService(
            runtime=_StaticRuntime(FAILOVER_YAML),
            cache=cache,
            stats_scope=store.scope("ratelimit").scope("service"),
            time_source=RealTimeSource(),
            lease=table,
        )
        return svc, cache, client, table, store, time_mod

    @staticmethod
    def _wait(cond, timeout=10.0, what="condition"):
        import time as time_mod

        deadline = time_mod.monotonic() + timeout
        while not cond():
            assert time_mod.monotonic() < deadline, f"timed out: {what}"
            time_mod.sleep(0.01)

    def test_leases_survive_promotion_with_replicated_floors(self, tmp_path):
        """Grants from the old primary keep answering locally through the
        crash; the promoted standby's replicated liability floors mean
        total admitted NEVER exceeds the limit (no double-grant), and
        settles land in the NEW primary's registry."""
        p_sock = str(tmp_path / "p.sock")
        s_sock = str(tmp_path / "s.sock")
        p_engine, p_coord, p_server = self._owner(p_sock, "primary")
        s_engine, s_coord, s_server = self._owner(
            s_sock, "standby", peer=p_sock
        )
        svc, cache, client, table, store, time_mod = self._frontend(
            [p_sock, s_sock]
        )
        errors: list[Exception] = []
        admitted = [0]

        def drive(n):
            for _ in range(n):
                try:
                    code, _, _ = svc.should_rate_limit(_req())
                except Exception as e:  # noqa: BLE001 - asserted empty
                    errors.append(e)
                else:
                    if code == Code.OK:
                        admitted[0] += 1

        try:
            drive(20)
            held, outstanding = table.outstanding()
            assert held == 1 and outstanding > 0
            # quiesce until the liability AND the slab have replicated
            self._wait(
                lambda: s_coord.replica_state()[1].shape[0] >= 1,
                what="liability replication",
            )
            time_mod.sleep(3.0 * self.INTERVAL_MS / 1e3)

            p_server.close()
            p_coord.close()

            # the outstanding lease answers locally with the owner DEAD
            budget = outstanding
            before_local = admitted[0]
            drive(min(budget, 4))
            assert errors == []
            assert admitted[0] == before_local + min(budget, 4)

            # past the budget: renewal fails over, the standby promotes
            # with the replicated floors, traffic continues
            drive(60)
            assert errors == [], errors[:3]
            assert s_coord.role == "primary"
            assert s_coord.promotions_total == 1

            # never over-admit: floors make the failover invisible to the
            # limit (50/hour; 80 requests sent; burn only under-admits)
            assert admitted[0] <= 50
            assert admitted[0] >= 45  # and burn stays small

            # settles land against the new epoch's registry
            self._wait(
                lambda: s_engine.lease_registry.settles_total > 0,
                what="settles on the new primary",
            )
        finally:
            client.close()
            for closer in (s_server.close, s_coord.close):
                closer()

    def test_lease_degraded_clears_once_standby_serves(self, tmp_path):
        """The sticky lease.degraded probe: raised while BOTH owners are
        unreachable and the frontend serves from outstanding leases,
        cleared by the first successful device interaction after the
        standby comes up and promotes."""
        from api_ratelimit_tpu.backends.sidecar import SlabSidecarServer

        p_sock = str(tmp_path / "p.sock")
        s_sock = str(tmp_path / "s.sock")
        p_engine, p_coord, p_server = self._owner(p_sock, "primary")
        # the standby COORDINATOR subscribes, but its server is not up
        # yet — so after the primary dies there is nowhere to fail over
        s_engine, s_coord, _ = self._owner(
            s_sock, "standby", peer=p_sock, start_server=False
        )
        svc, cache, client, table, store, time_mod = self._frontend(
            [p_sock, s_sock], retries=0, breaker_threshold=0
        )
        try:
            assert svc.should_rate_limit(_req())[0] == Code.OK  # grant
            self._wait(
                lambda: s_coord.replica_state()[0] is not None,
                what="standby sync",
            )
            p_server.close()
            p_coord.close()

            # budget answers locally; exhausting it needs the device ->
            # CacheError (no fallback configured) + sticky lease.degraded
            saw_error = False
            for _ in range(12):
                try:
                    svc.should_rate_limit(_req())
                except Exception:  # noqa: BLE001 - expected while dark
                    saw_error = True
                    break
            assert saw_error
            assert table.degraded
            assert "lease.degraded" in table.degraded_reason()

            # the standby's server comes up; the next device write fails
            # over, promotes it, succeeds — and the probe clears
            s_server = SlabSidecarServer(s_sock, s_engine, repl=s_coord)
            try:
                code, _, _ = svc.should_rate_limit(_req())
                assert code in (Code.OK, Code.OVER_LIMIT)
                assert s_coord.role == "primary"
                assert not table.degraded
                assert table.degraded_reason() is None
            finally:
                s_server.close()
        finally:
            client.close()
            s_coord.close()
