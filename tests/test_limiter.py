"""Limiter core + memory-oracle backend tests.

Scenario coverage mirrors the reference suites
(test/limiter/base_limiter_test.go, test/redis/fixed_cache_impl_test.go):
window key math pinned at a fixed timestamp, per-second flagging, local-cache
short-circuit with zero backend traffic, near/over-limit stats attribution,
and the ThrottleMillis pacing expectation (400000 in the canonical scenario).
"""

import random

import pytest

from api_ratelimit_tpu.backends import MemoryRateLimitCache
from api_ratelimit_tpu.limiter import BaseRateLimiter, LocalCache, generate_cache_key
from api_ratelimit_tpu.limiter.local_cache import LocalCacheStats
from api_ratelimit_tpu.models import (
    Code,
    Descriptor,
    RateLimitRequest,
    Unit,
)
from api_ratelimit_tpu.stats import Store, TestSink
from api_ratelimit_tpu.utils import FakeTimeSource


def make_limit(store, rpu, unit, key="key_value", **kw):
    # Build a rule directly through the models factory — no YAML plumbing.
    from api_ratelimit_tpu.models.config import RateLimit, new_rate_limit_stats
    from api_ratelimit_tpu.models.response import RateLimitValue

    return RateLimit(
        full_key=key,
        stats=new_rate_limit_stats(store, key),
        limit=RateLimitValue(requests_per_unit=rpu, unit=unit),
        **kw,
    )


@pytest.fixture
def store():
    return Store(TestSink())


def req(*pairs, hits=1, domain="domain"):
    return RateLimitRequest(
        domain=domain,
        descriptors=tuple(Descriptor.of(p) for p in pairs),
        hits_addend=hits,
    )


class TestCacheKey:
    def test_window_snapping(self, store):
        limit = make_limit(store, 10, Unit.SECOND)
        key = generate_cache_key("domain", Descriptor.of(("key", "value")), limit, 1234)
        assert key.key == "domain_key_value_1234"
        assert key.per_second is True

        limit_m = make_limit(store, 10, Unit.MINUTE)
        key = generate_cache_key("domain", Descriptor.of(("key", "value")), limit_m, 1234)
        assert key.key == "domain_key_value_1200"
        assert key.per_second is False

        limit_h = make_limit(store, 10, Unit.HOUR)
        assert (
            generate_cache_key("domain", Descriptor.of(("k", "v")), limit_h, 1000000).key
            == "domain_k_v_997200"
        )

    def test_multi_entry_and_nil_limit(self, store):
        limit = make_limit(store, 10, Unit.DAY)
        key = generate_cache_key(
            "domain",
            Descriptor.of(("a", "b"), ("c", "d")),
            limit,
            1234,
        )
        assert key.key == "domain_a_b_c_d_0"
        assert generate_cache_key("domain", Descriptor.of(("a", "b")), None, 1234).key == ""


def make_cache(store, now=1_000_000, local_cache_size=0, near_ratio=0.8, jitter_max=0):
    ts = FakeTimeSource(now)
    local = LocalCache(local_cache_size, ts) if local_cache_size else None
    base = BaseRateLimiter(
        ts,
        jitter_rand=random.Random(1),
        expiration_jitter_max_seconds=jitter_max,
        local_cache=local,
        near_limit_ratio=near_ratio,
    )
    return MemoryRateLimitCache(base), ts, local


class TestMemoryCacheDecisions:
    def test_under_near_at_near_over_and_local_cache(self, store):
        cache, ts, local = make_cache(store, local_cache_size=100)
        limit = make_limit(store, 15, Unit.HOUR, key="key4_value4")
        request = req(("key4", "value4"))

        # Counter 1..11: under near limit (floor(15*0.8)=12).
        for _ in range(11):
            resp = cache.do_limit(request, [limit])
        status = resp.descriptor_statuses[0]
        assert status.code == Code.OK
        assert status.limit_remaining == 4
        assert status.duration_until_reset == 800  # window ends at 1000800
        assert resp.throttle_millis == 0
        assert limit.stats.near_limit.value() == 0

        # 12th: at the near threshold, still no near-limit accounting.
        resp = cache.do_limit(request, [limit])
        assert resp.descriptor_statuses[0].code == Code.OK
        assert limit.stats.near_limit.value() == 0

        # 13th: near limit; pacing = 800000ms remaining / 2 calls = 400000.
        resp = cache.do_limit(request, [limit])
        assert resp.descriptor_statuses[0].code == Code.OK
        assert resp.descriptor_statuses[0].limit_remaining == 2
        assert resp.throttle_millis == 400_000
        assert limit.stats.near_limit.value() == 1

        # 14th, 15th: still OK.
        cache.do_limit(request, [limit])
        resp = cache.do_limit(request, [limit])
        assert resp.descriptor_statuses[0].limit_remaining == 0

        # 16th: over limit; near=3 (counts 13,14,15), over=1.
        resp = cache.do_limit(request, [limit])
        status = resp.descriptor_statuses[0]
        assert status.code == Code.OVER_LIMIT
        assert status.limit_remaining == 0
        assert limit.stats.over_limit.value() == 1
        assert limit.stats.near_limit.value() == 3
        assert limit.stats.over_limit_with_local_cache.value() == 0

        # 17th: served from the local over-limit cache — no backend touch.
        count_before = cache.peek("domain_key4_value4_997200")
        resp = cache.do_limit(request, [limit])
        assert resp.descriptor_statuses[0].code == Code.OVER_LIMIT
        assert cache.peek("domain_key4_value4_997200") == count_before
        assert limit.stats.over_limit.value() == 2
        assert limit.stats.over_limit_with_local_cache.value() == 1
        assert limit.stats.total_hits.value() == 17

    def test_hits_addend_attribution_split(self, store):
        # Call 1: hits=11 -> after=11 > near threshold 9: near += 11-9 = 2.
        # Call 2: before=11, addend=3 -> after=14 vs limit 12:
        # over += 14-12 = 2, near += 12 - max(9, 11) = 1 -> near total 3.
        cache, ts, _ = make_cache(store)
        limit = make_limit(store, 12, Unit.HOUR, key="k_v")
        request = req(("k", "v"), hits=11)
        cache.do_limit(request, [limit])
        assert limit.stats.near_limit.value() == 2
        resp = cache.do_limit(req(("k", "v"), hits=3), [limit])
        assert resp.descriptor_statuses[0].code == Code.OVER_LIMIT
        assert limit.stats.over_limit.value() == 2
        assert limit.stats.near_limit.value() == 3

        # Entirely-over addend: before=14 >= 12 -> all hits over.
        resp = cache.do_limit(req(("k", "v"), hits=5), [limit])
        assert limit.stats.over_limit.value() == 7

    def test_nil_limit_descriptor_unchecked(self, store):
        cache, _, _ = make_cache(store)
        limit = make_limit(store, 10, Unit.SECOND)
        resp = cache.do_limit(req(("a", "a"), ("b", "b")), [None, limit])
        assert resp.descriptor_statuses[0].code == Code.OK
        assert resp.descriptor_statuses[0].current_limit is None
        assert resp.descriptor_statuses[0].duration_until_reset is None
        assert resp.descriptor_statuses[1].code == Code.OK
        assert resp.descriptor_statuses[1].current_limit is not None

    def test_window_rollover_resets_counts(self, store):
        cache, ts, _ = make_cache(store)
        limit = make_limit(store, 2, Unit.SECOND, key="s")
        request = req(("s", "1"))
        cache.do_limit(request, [limit])
        cache.do_limit(request, [limit])
        resp = cache.do_limit(request, [limit])
        assert resp.descriptor_statuses[0].code == Code.OVER_LIMIT
        ts.advance(1)  # next second window -> new key -> fresh counter
        resp = cache.do_limit(request, [limit])
        assert resp.descriptor_statuses[0].code == Code.OK
        assert resp.descriptor_statuses[0].limit_remaining == 1

    def test_expiration_jitter(self, store):
        cache, ts, _ = make_cache(store, jitter_max=300)
        base = cache._base
        rng = random.Random(1)
        expected = 3600 + rng.randrange(300)
        assert base.expiration_seconds(3600) == expected

    def test_overall_multi_descriptor(self, store):
        cache, _, _ = make_cache(store)
        l1 = make_limit(store, 10, Unit.SECOND, key="l1")
        l2 = make_limit(store, 1, Unit.MINUTE, key="l2")
        request = req(("a", "1"), ("b", "2"), hits=2)
        resp = cache.do_limit(request, [l1, l2])
        codes = [s.code for s in resp.descriptor_statuses]
        assert codes == [Code.OK, Code.OVER_LIMIT]


class TestLocalCache:
    def test_ttl_and_stats(self, store):
        ts = FakeTimeSource(100)
        cache = LocalCache(max_entries=2, time_source=ts)
        stats = LocalCacheStats(cache, store.scope("localcache"))

        assert cache.contains("a") is False
        cache.set("a", ttl_seconds=10)
        assert cache.contains("a") is True
        ts.advance(10)
        assert cache.contains("a") is False  # expired exactly at ttl

        cache.set("x", 100)
        cache.set("y", 100)
        cache.set("z", 100)  # evicts oldest
        assert cache.entry_count() == 2

        stats.generate_stats()
        store.flush()
        sink = store._sink
        assert sink.gauges["localcache.hitCount"] == 1
        assert sink.gauges["localcache.missCount"] == 2
        assert sink.gauges["localcache.lookupCount"] == 3
        assert sink.gauges["localcache.expiredCount"] == 1
        assert sink.gauges["localcache.evacuateCount"] == 1


class TestShadowMode:
    """shadow_mode rules are evaluated and counted but never enforced
    (BASELINE configs[3]): breaches return OK, increment the shadow_mode
    counter, and skip the local over-limit cache so real traffic keeps
    being measured."""

    def test_breach_returns_ok_and_counts(self, store):
        cache, _, _ = make_cache(store)
        limit = make_limit(store, 2, Unit.HOUR, key="sh_v", shadow_mode=True)
        request = req(("sh", "v"))
        for _ in range(2):
            resp = cache.do_limit(request, [limit])
            assert resp.descriptor_statuses[0].code == Code.OK
        assert limit.stats.shadow_mode.value() == 0

        # 3rd..4th: would be OVER_LIMIT; shadow mode lets them through.
        for i in range(2):
            resp = cache.do_limit(request, [limit])
            status = resp.descriptor_statuses[0]
            assert status.code == Code.OK
            assert status.limit_remaining == 0
        # over-limit attribution still recorded, plus the shadow counter
        assert limit.stats.over_limit.value() == 2
        assert limit.stats.shadow_mode.value() == 2
        assert limit.stats.total_hits.value() == 4

    def test_local_cache_not_poisoned(self, store):
        cache, _, _ = make_cache(store, local_cache_size=100)
        limit = make_limit(store, 1, Unit.HOUR, key="sh2_v", shadow_mode=True)
        request = req(("sh2", "v"))
        cache.do_limit(request, [limit])
        resp = cache.do_limit(request, [limit])  # breach, shadowed
        assert resp.descriptor_statuses[0].code == Code.OK
        # the breach must NOT have seeded the over-limit cache: the next
        # call still reaches the backend and still evaluates
        assert limit.stats.over_limit_with_local_cache.value() == 0
        resp = cache.do_limit(request, [limit])
        assert resp.descriptor_statuses[0].code == Code.OK
        assert limit.stats.over_limit_with_local_cache.value() == 0
        assert limit.stats.shadow_mode.value() == 2

    def test_enforced_rule_unaffected(self, store):
        cache, _, _ = make_cache(store)
        shadowed = make_limit(store, 1, Unit.HOUR, key="s_v", shadow_mode=True)
        enforced = make_limit(store, 1, Unit.HOUR, key="e_v")
        request = req(("s", "v"), ("e", "v"))
        cache.do_limit(request, [shadowed, enforced])
        resp = cache.do_limit(request, [shadowed, enforced])
        codes = [s.code for s in resp.descriptor_statuses]
        assert codes == [Code.OK, Code.OVER_LIMIT]

    def test_reload_flip_ignores_stale_local_cache_entry(self, store):
        # A rule enforced long enough to seed the local over-limit cache,
        # then hot-reloaded to shadow_mode, must NOT keep short-circuiting:
        # the staged rule has to keep evaluating real traffic.
        cache, _, _ = make_cache(store, local_cache_size=100)
        enforced = make_limit(store, 1, Unit.HOUR, key="flip_v")
        request = req(("flip", "v"))
        cache.do_limit(request, [enforced])
        cache.do_limit(request, [enforced])  # breach -> cache seeded
        assert enforced.stats.over_limit.value() == 1

        # same rule, reloaded with shadow_mode on (new stats object, same key)
        staged = make_limit(store, 1, Unit.HOUR, key="flip_v", shadow_mode=True)
        resp = cache.do_limit(request, [staged])
        assert resp.descriptor_statuses[0].code == Code.OK
        # evaluated for real: backend counter advanced, no local-cache hit
        assert staged.stats.over_limit_with_local_cache.value() == 0
        assert staged.stats.shadow_mode.value() == 1
        # counters are shared by stats path: 1 from the enforced breach +
        # 1 from the freshly evaluated (not cache-served) staged breach
        assert staged.stats.over_limit.value() == 2
