"""Memcache backend tests against the in-process fake — the twin of
test/memcached/cache_impl_test.go: decide-from-read semantics, flush()
joining async increments, GetMulti error tolerance, the add/increment race,
and the 250-char key limit."""

import random

import pytest

from api_ratelimit_tpu.backends.memcache import (
    MemcacheClient,
    MemcacheError,
    MemcacheRateLimitCache,
    NotFoundError,
    NotStoredError,
)
from api_ratelimit_tpu.limiter.base_limiter import BaseRateLimiter
from api_ratelimit_tpu.models.config import RateLimit, new_rate_limit_stats
from api_ratelimit_tpu.models.descriptors import Descriptor, RateLimitRequest
from api_ratelimit_tpu.models.response import Code, RateLimitValue
from api_ratelimit_tpu.models.units import Unit
from api_ratelimit_tpu.stats import Store, TestSink
from api_ratelimit_tpu.testing.fake_memcache import FakeMemcacheServer
from api_ratelimit_tpu.utils import FakeTimeSource


@pytest.fixture
def fake_memcache():
    server = FakeMemcacheServer()
    yield server
    server.close()


def make_limit(scope, requests_per_unit, unit, key="k_v"):
    return RateLimit(
        full_key=key,
        limit=RateLimitValue(requests_per_unit, unit),
        stats=new_rate_limit_stats(scope, key),
    )


def make_cache(addr, now=1234):
    store = Store(TestSink())
    scope = store.scope("ratelimit")
    base = BaseRateLimiter(
        time_source=FakeTimeSource(now=now),
        jitter_rand=random.Random(0),
        expiration_jitter_max_seconds=0,
        local_cache=None,
        near_limit_ratio=0.8,
    )
    return MemcacheRateLimitCache(MemcacheClient(addr), base), scope


class TestClient:
    def test_get_multi_and_incr_add(self, fake_memcache):
        client = MemcacheClient(fake_memcache.addr)
        assert client.get_multi(["a", "b"]) == {}
        client.add("a", 5, 60)
        assert client.get_multi(["a", "b"]) == {"a": 5}
        assert client.increment("a", 3) == 8
        with pytest.raises(NotFoundError):
            client.increment("missing", 1)
        with pytest.raises(NotStoredError):
            client.add("a", 1, 60)

    def test_key_length_limit(self, fake_memcache):
        client = MemcacheClient(fake_memcache.addr)
        with pytest.raises(MemcacheError, match="too long"):
            client.increment("x" * 251, 1)


class TestMemcacheCache:
    def test_decides_from_read_then_settles_async(self, fake_memcache):
        """after = fetched + hits decides NOW; the increment lands async
        (cache_impl.go:95-125)."""
        cache, scope = make_cache(fake_memcache.addr)
        limit = make_limit(scope, 2, Unit.MINUTE)
        req = RateLimitRequest(domain="d", descriptors=(Descriptor.of(("k", "v")),))

        r1 = cache.do_limit(req, [limit])
        assert r1.descriptor_statuses[0].code == Code.OK
        assert r1.descriptor_statuses[0].limit_remaining == 1
        cache.flush()
        assert fake_memcache.get_int("d_k_v_1200") == 1

        r2 = cache.do_limit(req, [limit])
        assert r2.descriptor_statuses[0].code == Code.OK
        cache.flush()
        r3 = cache.do_limit(req, [limit])
        assert r3.descriptor_statuses[0].code == Code.OVER_LIMIT
        cache.flush()
        assert fake_memcache.get_int("d_k_v_1200") == 3

    def test_eventual_consistency_window(self, fake_memcache):
        """Without flush(), two concurrent reads may both admit — the
        documented memcache trade-off (README.md:567-568). Simulated by
        pre-seeding the fetched value."""
        cache, scope = make_cache(fake_memcache.addr)
        limit = make_limit(scope, 1, Unit.MINUTE)
        req = RateLimitRequest(domain="d", descriptors=(Descriptor.of(("k", "v")),))
        # both calls read before either increment lands => both OK
        r1 = cache.do_limit(req, [limit])
        r2 = cache.do_limit(req, [limit])
        assert r1.descriptor_statuses[0].code == Code.OK
        assert r2.descriptor_statuses[0].code in (Code.OK, Code.OVER_LIMIT)

    def test_get_error_tolerated_as_zero(self):
        """Backend down: counts read as 0 => request allowed; increments
        dropped (cache_impl.go:96-99) — fail-open, unlike redis."""
        cache, scope = make_cache("127.0.0.1:1")
        limit = make_limit(scope, 2, Unit.MINUTE)
        req = RateLimitRequest(domain="d", descriptors=(Descriptor.of(("k", "v")),))
        resp = cache.do_limit(req, [limit])
        assert resp.descriptor_statuses[0].code == Code.OK
        cache.flush()  # async increment failures must not raise

    def test_add_increment_race(self, fake_memcache):
        """Increment -> NOT_FOUND -> Add -> NOT_STORED (lost race) ->
        Increment again (cache_impl.go:130-168; TestMemcacheAdd)."""
        cache, scope = make_cache(fake_memcache.addr)
        limit = make_limit(scope, 10, Unit.MINUTE)
        req = RateLimitRequest(domain="d", descriptors=(Descriptor.of(("k", "v")),))
        fake_memcache.force_not_stored_once = True
        cache.do_limit(req, [limit])
        cache.flush()
        # the fake seeds 0 on the forced NOT_STORED add, so the retry
        # increment must have applied our hit on top
        assert fake_memcache.get_int("d_k_v_1200") == 1
        incrs = [c for c in fake_memcache.commands_seen if c.startswith(b"incr")]
        assert len(incrs) == 2  # initial miss + post-race retry

    def test_expiry_set_on_add(self, fake_memcache):
        cache, scope = make_cache(fake_memcache.addr)
        limit = make_limit(scope, 10, Unit.MINUTE)
        req = RateLimitRequest(domain="d", descriptors=(Descriptor.of(("k", "v")),))
        cache.do_limit(req, [limit])
        cache.flush()
        adds = [c for c in fake_memcache.commands_seen if c.startswith(b"add")]
        assert len(adds) == 1
        assert adds[0].split()[3] == b"60"  # exptime = MINUTE divider


class TestWireRobustness:
    """Corrupt server replies must surface as MemcacheError or be
    tolerated per the backend's documented fail-open behavior — never as
    IndexError/UnicodeDecodeError/ValueError out of the in-repo client
    (the analog of the RESP-parser hardening on the redis side)."""

    @staticmethod
    def _client_with_reply(reply: bytes):
        import socket
        import threading

        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)

        def serve():
            conn, _ = srv.accept()
            conn.recv(65536)
            conn.sendall(reply)
            conn.close()

        threading.Thread(target=serve, daemon=True).start()
        host, port = srv.getsockname()
        return MemcacheClient(f"{host}:{port}")

    def test_get_multi_truncated_value_line(self):
        c = self._client_with_reply(b"VALUE\r\nEND\r\n")
        assert c.get_multi(["a"]) == {}

    def test_get_multi_binary_key(self):
        c = self._client_with_reply(b"VALUE \xff\xfe 0 1\r\n7\r\nEND\r\n")
        assert c.get_multi(["a"]) == {}

    def test_get_multi_value_without_data_line(self):
        c = self._client_with_reply(b"VALUE a 0 1\r\nEND\r\n")
        assert c.get_multi(["a"]) == {}

    def test_incr_garbage_reply(self):
        c = self._client_with_reply(b"WAT\r\n")
        with pytest.raises(MemcacheError, match="bad incr reply"):
            c.increment("a", 1)
