"""Fast tier-1 wrapper around tools/metrics_lint.py: the package's literal
stat-name registrations must keep the dotted-lowercase convention and one
stat kind per name (a counter/gauge clash would make the Prometheus
renderer emit two # TYPE declarations for one family)."""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_linter():
    spec = importlib.util.spec_from_file_location(
        "metrics_lint", os.path.join(REPO, "tools", "metrics_lint.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_package_stat_names_are_clean():
    lint = _load_linter()
    findings = lint.lint()
    assert findings == [], "\n".join(findings)
    # sanity: the walker actually saw the known registrations — an empty
    # scan passing would make this lint vacuous
    names = {name for name, _, _, _ in lint.iter_registrations()}
    assert "config_load_success" in names
    assert "queue_wait_ms" in names


def test_linter_flags_violations(tmp_path):
    lint = _load_linter()
    bad = tmp_path / "bad_stats.py"
    bad.write_text(
        'a = scope.counter("CamelCase.name")\n'
        'b = scope.counter("dup.name")\n'
        'c = scope.gauge("dup.name")\n'
    )
    findings = lint.lint(str(tmp_path))
    assert any("CamelCase.name" in f and "convention" in f for f in findings)
    assert any("dup.name" in f and "conflicting types" in f for f in findings)


def test_multiline_registrations_are_seen(tmp_path):
    """A registration whose string literal sits on a continuation line
    (black-style wrapping) must still be scanned — README drift checking
    depends on the walker seeing every literal."""
    lint = _load_linter()
    (tmp_path / "wrapped.py").write_text(
        "h = scope.histogram(\n"
        '    "wrapped_name", boundaries=BUCKETS\n'
        ")\n"
    )
    names = {n for n, _, _, _ in lint.iter_registrations(str(tmp_path))}
    assert names == {"wrapped_name"}


def test_readme_metric_names_exist_in_source():
    """Drift check: every ratelimit.* metric documented in README.md must
    still be registered somewhere in the package."""
    lint = _load_linter()
    names = lint.readme_metric_names()
    # sanity: the extractor actually parses the README tables (an empty
    # list would make the drift check vacuous)
    assert "ratelimit.batcher.queue_wait_ms" in names
    assert "ratelimit.fallback.degraded" in names  # PR-2 ladder gauge
    findings = lint.lint_readme()
    assert findings == [], "\n".join(findings)


def test_readme_drift_is_flagged(tmp_path):
    lint = _load_linter()
    (tmp_path / "stats.py").write_text('a = scope.counter("real_name")\n')
    readme = tmp_path / "README.md"
    readme.write_text(
        "| `ratelimit.x.real_name` | fine |\n"
        "| `ratelimit.x.ghost_name` | gone |\n"
        "| `ratelimit.y.{real_name,ghost_name}` | brace expansion |\n"
        "| `ratelimit.z.<domain>.anything` | placeholder skipped |\n"
    )
    findings = lint.lint_readme(str(tmp_path), str(readme))
    assert len(findings) == 2
    assert all("ghost_name" in f for f in findings)
