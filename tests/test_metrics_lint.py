"""Fast tier-1 wrapper around tools/metrics_lint.py: the package's literal
stat-name registrations must keep the dotted-lowercase convention and one
stat kind per name (a counter/gauge clash would make the Prometheus
renderer emit two # TYPE declarations for one family)."""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_linter():
    spec = importlib.util.spec_from_file_location(
        "metrics_lint", os.path.join(REPO, "tools", "metrics_lint.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_package_stat_names_are_clean():
    lint = _load_linter()
    findings = lint.lint()
    assert findings == [], "\n".join(findings)
    # sanity: the walker actually saw the known registrations — an empty
    # scan passing would make this lint vacuous
    names = {name for name, _, _, _ in lint.iter_registrations()}
    assert "config_load_success" in names
    assert "queue_wait_ms" in names


def test_linter_flags_violations(tmp_path):
    lint = _load_linter()
    bad = tmp_path / "bad_stats.py"
    bad.write_text(
        'a = scope.counter("CamelCase.name")\n'
        'b = scope.counter("dup.name")\n'
        'c = scope.gauge("dup.name")\n'
    )
    findings = lint.lint(str(tmp_path))
    assert any("CamelCase.name" in f and "convention" in f for f in findings)
    assert any("dup.name" in f and "conflicting types" in f for f in findings)
