"""Native host-codec parity tests: the C++ library (native/host_codec.cpp)
must produce bit-identical fingerprints and byte-identical cache keys to the
pure-Python implementations — slab slot identity may not depend on which
host path computed it. Mirrors the reference's exact-wire-command assertions
at the backend seam (test/redis/fixed_cache_impl_test.go:59-64)."""

from __future__ import annotations

import os
import random
import string

import numpy as np
import pytest

from api_ratelimit_tpu.ops import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native codec unavailable (no g++?)"
)


class TestPackScatterParity:
    """rl_pack_rows / rl_scatter_rows (the dispatch loop's gather/scatter
    stages) vs the numpy fallback: byte-identical operands and verdicts,
    including non-contiguous arena-slice sources."""

    def test_pack_rows_matches_numpy_copy_loop(self):
        rng = np.random.RandomState(7)
        # mix of contiguous blocks and column slices of a wider arena
        arena = rng.randint(0, 2**32, size=(6, 64), dtype=np.uint64).astype(
            np.uint32
        )
        blocks = [
            np.ascontiguousarray(
                rng.randint(0, 2**32, size=(6, 3), dtype=np.uint64).astype(
                    np.uint32
                )
            ),
            arena[:, 10:14],  # row stride 64, not 4
            arena[:, 30:31],
            np.ascontiguousarray(
                rng.randint(0, 2**32, size=(6, 5), dtype=np.uint64).astype(
                    np.uint32
                )
            ),
        ]
        total = sum(b.shape[1] for b in blocks)
        want = np.zeros((7, 16), dtype=np.uint32)
        off = 0
        for b in blocks:
            want[:6, off : off + b.shape[1]] = b
            off += b.shape[1]
        got = np.zeros((7, 16), dtype=np.uint32)
        native.pack_rows(blocks, got, total)
        assert got.tobytes() == want.tobytes()

    def test_pack_rows_bounds_checked(self):
        blocks = [np.zeros((6, 9), dtype=np.uint32)]
        dst = np.zeros((7, 8), dtype=np.uint32)
        with pytest.raises(ValueError, match="exceed"):
            native.pack_rows(blocks, dst, 9)

    def test_scatter_rows_matches_numpy_slices(self):
        rng = np.random.RandomState(8)
        src = rng.randint(0, 2**32, size=24, dtype=np.uint64).astype(np.uint32)
        counts = [3, 1, 12, 8]
        dsts = [np.zeros(c, dtype=np.uint32) for c in counts]
        native.scatter_rows(src, dsts, counts)
        off = 0
        for d, c in zip(dsts, counts):
            assert d.tolist() == src[off : off + c].tolist()
            off += c

    def test_scatter_rows_bounds_checked(self):
        src = np.zeros(4, dtype=np.uint32)
        with pytest.raises(ValueError, match="exceed"):
            native.scatter_rows(
                src, [np.zeros(5, dtype=np.uint32)], [5]
            )


def _rand_text(rng, n):
    alphabet = string.ascii_letters + string.digits + "_-./:é中"
    return "".join(rng.choice(alphabet) for _ in range(n))


class TestXxh64Parity:
    @pytest.mark.parametrize("n", [0, 1, 3, 4, 7, 8, 15, 31, 32, 33, 100, 4096])
    @pytest.mark.parametrize("seed", [0, 1, 60, 86400, 2**64 - 1])
    def test_matches_python_xxhash(self, n, seed):
        import xxhash

        data = os.urandom(n)
        assert native.xxh64(data, seed) == xxhash.xxh64(data, seed=seed).intdigest()


class TestFingerprintBatchParity:
    def test_matches_python_fingerprint64(self):
        from api_ratelimit_tpu.models.descriptors import Entry
        from api_ratelimit_tpu.ops.hashing import fingerprint64

        rng = random.Random(7)
        records = []
        seeds = []
        expected = []
        for _ in range(200):
            domain = _rand_text(rng, rng.randint(0, 20))
            entries = tuple(
                Entry(_rand_text(rng, rng.randint(0, 30)), _rand_text(rng, rng.randint(0, 30)))
                for _ in range(rng.randint(0, 4))
            )
            divider = rng.choice([1, 60, 3600, 86400])
            records.append(native.record_strings(domain, entries))
            seeds.append(divider)
            expected.append(fingerprint64(domain, entries, divider))
        got = native.fingerprint_batch(records, seeds)
        assert got.dtype == np.uint64
        assert [int(x) for x in got] == expected

    def test_empty_strings_and_aliasing(self):
        # length prefixes must prevent ("ab","") from aliasing ("a","b")
        from api_ratelimit_tpu.models.descriptors import Entry
        from api_ratelimit_tpu.ops.hashing import fingerprint64

        a = native.fingerprint_batch(
            [native.record_strings("d", (Entry("ab", ""),))], [60]
        )[0]
        b = native.fingerprint_batch(
            [native.record_strings("d", (Entry("a", "b"),))], [60]
        )[0]
        assert a != b
        assert int(a) == fingerprint64("d", (Entry("ab", ""),), 60)

    def test_fingerprint_many_dispatches_native(self):
        from api_ratelimit_tpu.models.descriptors import Entry
        from api_ratelimit_tpu.ops.hashing import fingerprint64, fingerprint_many

        records = [
            ("domain", (Entry("key1", f"val{i}"),)) for i in range(16)
        ]
        dividers = [60] * 16
        got = fingerprint_many(records, dividers)
        want = [fingerprint64(d, e, 60) for d, e in records]
        assert [int(x) for x in got] == want

    def test_fingerprint_many_small_batch_python_path(self):
        from api_ratelimit_tpu.models.descriptors import Entry
        from api_ratelimit_tpu.ops.hashing import fingerprint64, fingerprint_many

        records = [("d", (Entry("k", "v"),))]
        got = fingerprint_many(records, [1])
        assert int(got[0]) == fingerprint64("d", (Entry("k", "v"),), 1)


class TestComposeKeysParity:
    def test_matches_python_codec(self):
        from api_ratelimit_tpu.limiter.cache_key import generate_cache_key
        from api_ratelimit_tpu.models.config import RateLimit
        from api_ratelimit_tpu.models.descriptors import Descriptor, Entry
        from api_ratelimit_tpu.models.response import RateLimitValue
        from api_ratelimit_tpu.models.units import Unit, unit_to_divider

        rng = random.Random(13)
        records = []
        windows = []
        expected = []
        for _ in range(100):
            domain = _rand_text(rng, rng.randint(1, 15))
            entries = tuple(
                Entry(_rand_text(rng, rng.randint(1, 10)), _rand_text(rng, rng.randint(0, 10)))
                for _ in range(rng.randint(1, 3))
            )
            unit = rng.choice([Unit.SECOND, Unit.MINUTE, Unit.HOUR, Unit.DAY])
            limit = RateLimit(
                full_key="x",
                stats=None,
                limit=RateLimitValue(requests_per_unit=10, unit=unit),
            )
            now = rng.randint(0, 2**31 - 1)
            divider = unit_to_divider(unit)
            records.append(native.record_strings(domain, entries))
            windows.append((now // divider) * divider)
            expected.append(
                generate_cache_key(domain, Descriptor(entries=entries), limit, now).key
            )
        got = native.compose_keys_batch(records, windows)
        assert got == expected

    def test_window_zero(self):
        got = native.compose_keys_batch([["d", "k", "v"]], [0])
        assert got == ["d_k_v_0"]

    def test_window_negative_matches_python_str(self):
        # pre-epoch/skewed clocks must render like Python's str()
        got = native.compose_keys_batch(
            [["d", "k", "v"], ["d", "k", "v"]], [-60, -9223372036854775808]
        )
        assert got == ["d_k_v_-60", "d_k_v_-9223372036854775808"]

    def test_seed_count_mismatch_raises(self):
        with pytest.raises(ValueError):
            native.fingerprint_batch([["d"], ["e"]], [1])

    def test_generate_cache_keys_native_batch_parity(self, test_store):
        # >=8 checked descriptors routes through the native composer; keys
        # must match the per-descriptor Python codec exactly, with nil
        # limits interleaved as empty keys
        from api_ratelimit_tpu.limiter.base_limiter import BaseRateLimiter
        from api_ratelimit_tpu.limiter.cache_key import generate_cache_key
        from api_ratelimit_tpu.models.config import RateLimit, new_rate_limit_stats
        from api_ratelimit_tpu.models.descriptors import (
            Descriptor,
            RateLimitRequest,
        )
        from api_ratelimit_tpu.models.response import RateLimitValue
        from api_ratelimit_tpu.models.units import Unit
        from api_ratelimit_tpu.utils.timeutil import FakeTimeSource

        store, _ = test_store
        scope = store.scope("t")
        descriptors = []
        limits = []
        for i in range(12):
            descriptors.append(Descriptor.of(("key", f"v{i}"), ("sub", "x")))
            if i % 5 == 4:
                limits.append(None)  # unchecked descriptor
            else:
                limits.append(
                    RateLimit(
                        full_key=f"k{i}",
                        stats=new_rate_limit_stats(scope, f"k{i}"),
                        limit=RateLimitValue(
                            requests_per_unit=10,
                            unit=Unit.SECOND if i % 2 else Unit.HOUR,
                        ),
                    )
                )
        ts = FakeTimeSource(987_654_321)
        base = BaseRateLimiter(time_source=ts, jitter_rand=None)
        request = RateLimitRequest(
            domain="paritydom", descriptors=tuple(descriptors)
        )
        got = base.generate_cache_keys(request, limits, 1)
        want = [
            generate_cache_key("paritydom", d, lim, 987_654_321)
            for d, lim in zip(descriptors, limits)
        ]
        assert got == want

    def test_output_buffer_growth(self):
        # force the retry path with a huge value string
        big = "v" * 100_000
        got = native.compose_keys_batch([["d", "k", big]], [1234])
        assert got == [f"d_k_{big}_1234"]
