"""Overload admission-control suite (the pressure-side twin of test_chaos):
deadline propagation (expired work never reaches a device launch), the
bounded batcher queue, the latency brownout with enter/exit hysteresis,
each shed posture at the service level and over real gRPC, slab-saturation
watermarks with the expired-slot sweep, and drain-under-load shedding the
throttle sleep instead of pinning workers.
"""

from __future__ import annotations

import threading
import time

import pytest

from api_ratelimit_tpu.backends.batcher import MicroBatcher
from api_ratelimit_tpu.backends.overload import (
    SHED_MODE_ALLOW,
    SHED_MODE_DENY,
    SHED_MODE_UNAVAILABLE,
    AdmissionController,
    BrownoutError,
    OverloadError,
    QueueFullError,
)
from api_ratelimit_tpu.backends.tpu import SlabDeviceEngine, _Item
from api_ratelimit_tpu.limiter.cache import DeadlineExceededError
from api_ratelimit_tpu.models import Code, Descriptor, RateLimitRequest
from api_ratelimit_tpu.models.response import DescriptorStatus, DoLimitResponse
from api_ratelimit_tpu.service import RateLimitService
from api_ratelimit_tpu.stats import Store, TestSink
from api_ratelimit_tpu.testing.faults import FaultInjector, parse_fault_spec
from api_ratelimit_tpu.utils import FakeTimeSource
from api_ratelimit_tpu.utils.deadline import deadline_scope, time_remaining


# -- harness (mirrors test_service / test_chaos) -----------------------------


class _FakeRuntime:
    def __init__(self, files):
        self._files = dict(files)

    def snapshot(self):
        files = self._files

        class Snap:
            def keys(self):
                return list(files)

            def get(self, key):
                return files[key]

        return Snap()

    def add_update_callback(self, cb):
        pass


class _FakeCache:
    def __init__(self):
        self.calls = 0
        self.raise_error = None
        self.next_throttle = 0

    def do_limit(self, request, limits):
        self.calls += 1
        if self.raise_error is not None:
            raise self.raise_error
        return DoLimitResponse(
            descriptor_statuses=[
                DescriptorStatus(code=Code.OK) for _ in request.descriptors
            ],
            throttle_millis=self.next_throttle,
        )

    def flush(self):
        pass


OVERLOAD_YAML = """
domain: overload
descriptors:
  - key: k
    value: v
    rate_limit: {unit: minute, requests_per_unit: 10}
"""

SLEEPY_YAML = """
domain: sleepy
descriptors:
  - key: k
    value: v
    rate_limit: {unit: minute, requests_per_unit: 10}
    sleep_on_throttle: true
    report_details: true
"""


def _req(domain="overload"):
    return RateLimitRequest(
        domain=domain,
        descriptors=(Descriptor.of(("k", "v")),),
        hits_addend=1,
    )


def _service(store, overload=None, cache=None, files=None, **kw):
    cache = cache or _FakeCache()
    svc = RateLimitService(
        runtime=_FakeRuntime(
            files if files is not None else {"config.ov": OVERLOAD_YAML}
        ),
        cache=cache,
        stats_scope=store.scope("ratelimit").scope("service"),
        time_source=FakeTimeSource(1_000_000),
        overload=overload,
        **kw,
    )
    return svc, cache


def _controller(store, **kw):
    kw.setdefault("shed_mode", SHED_MODE_UNAVAILABLE)
    return AdmissionController(scope=store.scope("ratelimit"), **kw)


def _brownout(controller):
    """Force the controller into brownout via its own EWMA machinery."""
    for _ in range(8):
        controller.observe_queue_wait(1e6)
    assert controller.brownout


# -- deadline propagation ----------------------------------------------------


class TestDeadlineContext:
    def test_no_scope_means_no_deadline(self):
        assert time_remaining() is None

    def test_scope_sets_and_restores(self):
        with deadline_scope(5.0):
            remaining = time_remaining()
            assert remaining is not None and 4.0 < remaining <= 5.0
            with deadline_scope(0.1):
                assert time_remaining() <= 0.1
            assert time_remaining() > 4.0
        assert time_remaining() is None


class TestBatcherDeadline:
    def test_direct_mode_expired_sheds_before_execute(self):
        executed = []
        b = MicroBatcher(lambda items: executed.append(items) or [0] * len(items))
        with deadline_scope(-0.001):
            with pytest.raises(DeadlineExceededError):
                b.submit([1])
        assert executed == []
        assert b.deadline_drops == 1
        # without a deadline the same submit executes
        assert b.submit([1]) == [0]

    def test_windowed_expired_items_never_reach_a_launch(self):
        """The tentpole invariant: an expired request's items are dropped
        at take time — they resolve as shed and never consume batch
        slots — while fresh requests in the same window still execute."""
        launched: list = []

        def execute(items):
            launched.extend(items)
            return [0] * len(items)

        b = MicroBatcher(execute, window_seconds=0.02)
        results = {}

        def worker(name, remaining):
            def run():
                try:
                    with deadline_scope(remaining):
                        results[name] = b.submit([name])
                except DeadlineExceededError:
                    results[name] = "expired"

            t = threading.Thread(target=run)
            t.start()
            return t

        threads = [worker("dead", -0.001), worker("live", None)]
        for t in threads:
            t.join(10.0)
        b.close()
        assert results["dead"] == "expired"
        assert results["live"] == [0]
        assert launched == ["live"]
        assert b.deadline_drops == 1

    def test_service_sheds_expired_before_cache(self, test_store):
        store, _ = test_store
        controller = _controller(store)
        svc, cache = _service(store, overload=controller)
        with deadline_scope(-0.001):
            with pytest.raises(DeadlineExceededError):
                svc.should_rate_limit(_req())
        assert cache.calls == 0  # shed before any backend work
        snap = store.debug_snapshot()
        assert snap["ratelimit.overload.deadline_expired"] == 1
        # not a backend failure: redis_error stays untouched
        assert (
            snap["ratelimit.service.call.should_rate_limit.redis_error"] == 0
        )


# -- bounded queue + fault site ----------------------------------------------


class TestQueueBound:
    def test_max_queue_sheds_instantly_while_stalled(self):
        """With the executor wedged, submits past max_queue answer
        immediately with QueueFullError instead of queueing unbounded."""
        start = threading.Event()
        release = threading.Event()

        def execute(items):
            start.set()
            assert release.wait(10.0)
            return [0] * len(items)

        b = MicroBatcher(execute, window_seconds=0.005, max_queue=2)
        stalled = threading.Thread(target=lambda: b.submit(["a"]))
        stalled.start()
        assert start.wait(5.0)  # dispatcher is now wedged in execute()
        waiters = [
            threading.Thread(target=lambda: b.submit(["b"])),
            threading.Thread(target=lambda: b.submit(["c"])),
        ]
        for t in waiters:
            t.start()
        deadline = time.monotonic() + 5.0
        while b.queue_depth < 2 and time.monotonic() < deadline:
            time.sleep(0.001)
        assert b.queue_depth == 2
        t0 = time.monotonic()
        with pytest.raises(QueueFullError):
            b.submit(["d"])
        assert time.monotonic() - t0 < 1.0  # shed instantly, no queueing
        release.set()
        stalled.join(10.0)
        for t in waiters:
            t.join(10.0)
        b.close()

    def test_injected_queue_full_fault(self):
        faults = FaultInjector(parse_fault_spec("batcher.submit:queue_full:1.0"))
        b = MicroBatcher(lambda items: [0] * len(items), fault_injector=faults)
        with pytest.raises(QueueFullError, match="injected"):
            b.submit([1])
        assert faults.fired() == {"batcher.submit:queue_full": 1}

    def test_injected_delay_stalls_submit(self):
        slept = []
        faults = FaultInjector(
            parse_fault_spec("batcher.submit:delay_ms:250"), sleep=slept.append
        )
        b = MicroBatcher(lambda items: [0] * len(items), fault_injector=faults)
        assert b.submit([1]) == [0]
        assert slept == [0.25]


# -- brownout hysteresis -----------------------------------------------------


class TestBrownoutHysteresis:
    def test_enter_and_exit_with_hysteresis(self, test_store):
        store, _ = test_store
        c = _controller(
            store,
            brownout_target_ms=5.0,
            brownout_exit_ms=2.0,
            ewma_alpha=1.0,  # EWMA == last sample: deterministic
        )
        assert not c.brownout
        c.observe_queue_wait(10.0)
        assert c.brownout  # 10 > 5: enter
        c.observe_queue_wait(3.0)
        assert c.brownout  # 3 in (2, 5]: hysteresis holds it in
        c.observe_queue_wait(1.0)
        assert not c.brownout  # 1 < 2: exit
        snap = store.debug_snapshot()
        assert snap["ratelimit.overload.brownout"] == 0
        assert snap["ratelimit.overload.queue_wait_ewma_us"] == 1000

    def test_default_exit_is_half_target(self, test_store):
        store, _ = test_store
        c = _controller(store, brownout_target_ms=10.0, ewma_alpha=1.0)
        c.observe_queue_wait(11.0)
        assert c.brownout
        c.observe_queue_wait(6.0)  # above 10/2: still browned out
        assert c.brownout
        c.observe_queue_wait(4.0)  # below 10/2: out
        assert not c.brownout

    def test_degraded_reason_while_browned_out(self, test_store):
        store, _ = test_store
        c = _controller(store, brownout_target_ms=5.0, ewma_alpha=1.0)
        assert c.degraded_reason() is None
        c.observe_queue_wait(50.0)
        assert "brownout" in c.degraded_reason()

    def test_batcher_sheds_during_brownout(self, test_store):
        store, _ = test_store
        c = _controller(store, brownout_target_ms=1.0, ewma_alpha=1.0)
        _brownout(c)
        b = MicroBatcher(lambda items: [0] * len(items), overload=c)
        with pytest.raises(BrownoutError):
            b.submit([1])

    def test_validation(self, test_store):
        store, _ = test_store
        with pytest.raises(ValueError, match="hysteresis"):
            _controller(
                store, brownout_target_ms=5.0, brownout_exit_ms=5.0
            )
        with pytest.raises(ValueError, match="alpha"):
            _controller(store, ewma_alpha=0.0)
        with pytest.raises(ValueError, match="shed mode"):
            AdmissionController(shed_mode="nope")


# -- shed postures at the service level --------------------------------------


class TestShedPostures:
    def _browned_service(self, store, mode):
        controller = _controller(
            store, shed_mode=mode, brownout_target_ms=1.0, ewma_alpha=1.0
        )
        _brownout(controller)
        svc, cache = _service(store, overload=controller)
        return svc, cache, controller

    def test_allow_posture_fails_open_with_shed_header(self, test_store):
        store, sink = test_store
        svc, cache, controller = self._browned_service(store, SHED_MODE_ALLOW)
        overall, statuses, headers = svc.should_rate_limit(_req())
        assert overall == Code.OK
        assert statuses[0].code == Code.OK
        assert any(
            h.key == "x-ratelimit-shed" and h.value == "brownout"
            for h in headers
        )
        assert cache.calls == 0  # shed pre-dispatch
        store.flush()
        assert sink.counters["ratelimit.overload.shed"] == 1
        assert sink.counters["ratelimit.overload.brownout_shed"] == 1
        assert sink.gauges["ratelimit.overload.shedding"] == 1
        assert "overload" in controller.degraded_reason()

    def test_deny_posture_answers_over_limit(self, test_store):
        store, sink = test_store
        svc, _, _ = self._browned_service(store, SHED_MODE_DENY)
        overall, statuses, _ = svc.should_rate_limit(_req())
        assert overall == Code.OVER_LIMIT
        assert statuses[0].code == Code.OVER_LIMIT
        store.flush()
        assert sink.counters["ratelimit.overload.shed"] == 1

    def test_unavailable_posture_raises(self, test_store):
        store, sink = test_store
        svc, _, _ = self._browned_service(store, SHED_MODE_UNAVAILABLE)
        with pytest.raises(BrownoutError):
            svc.should_rate_limit(_req())
        store.flush()
        # counted as shed, NOT as a backend failure
        assert sink.counters["ratelimit.overload.shed"] == 1
        assert (
            sink.counters.get(
                "ratelimit.service.call.should_rate_limit.redis_error", 0
            )
            == 0
        )

    def test_backend_overload_error_answers_by_posture(self, test_store):
        """An OverloadError surfacing from the cache layer (rather than
        the batcher's own admission check) is a shed, not a backend
        failure: the posture answers it."""
        store, sink = test_store
        controller = _controller(store, shed_mode=SHED_MODE_ALLOW)
        svc, cache = _service(store, overload=controller)
        cache.raise_error = QueueFullError("ring full")
        overall, _, headers = svc.should_rate_limit(_req())
        assert overall == Code.OK
        assert any(
            h.key == "x-ratelimit-shed" and h.value == "queue_full"
            for h in headers
        )
        store.flush()
        assert sink.counters["ratelimit.overload.queue_full"] == 1

    def test_no_controller_reraises_overload(self, test_store):
        store, _ = test_store
        svc, cache = _service(store, overload=None)
        cache.raise_error = QueueFullError("full")
        with pytest.raises(OverloadError):
            svc.should_rate_limit(_req())

    def test_shed_state_clears_on_next_admitted_request(self, test_store):
        store, sink = test_store
        controller = _controller(store, shed_mode=SHED_MODE_ALLOW)
        svc, cache = _service(store, overload=controller)
        cache.raise_error = QueueFullError("full")
        svc.should_rate_limit(_req())
        assert controller.degraded_reason() is not None
        cache.raise_error = None
        svc.should_rate_limit(_req())
        assert controller.degraded_reason() is None
        store.flush()
        assert sink.gauges["ratelimit.overload.shedding"] == 0

    def test_healthcheck_stacks_overload_and_fallback_probes(self, test_store):
        from api_ratelimit_tpu.server.health import HealthChecker

        store, _ = test_store
        controller = _controller(store, shed_mode=SHED_MODE_ALLOW)
        health = HealthChecker()
        health.add_degraded_probe(controller.degraded_reason)
        assert health.http_response() == (200, "OK")
        controller.note_shed(QueueFullError("full"))
        status, body = health.http_response()
        assert status == 200  # shedding still serves; never drained
        assert body.startswith("OK") and "overload" in body
        controller.note_ok()
        assert health.http_response() == (200, "OK")


# -- throttle-sleep hardening ------------------------------------------------


class TestSleepShed:
    def test_draining_skips_sleep_and_counts(self, test_store):
        store, sink = test_store
        svc, cache = _service(
            store,
            files={"config.sleepy": SLEEPY_YAML},
            max_sleeping_routines=2,
            draining_probe=lambda: True,
        )
        cache.next_throttle = 1500
        _, _, headers = svc.should_rate_limit(_req(domain="sleepy"))
        assert svc._time_source.sleeps == []  # never pinned a worker
        # not slept server-side: the throttle header reaches the client
        assert any(h.key == "x-ratelimit-throttle-ms" for h in headers)
        store.flush()
        assert (
            sink.counters["ratelimit.service.call.should_rate_limit.sleep_shed"]
            == 1
        )

    def test_exhausted_semaphore_counts_sleep_shed(self, test_store):
        store, sink = test_store
        svc, cache = _service(
            store,
            files={"config.sleepy": SLEEPY_YAML},
            max_sleeping_routines=1,
        )
        cache.next_throttle = 1500
        assert svc._sleeper_semaphore.acquire(blocking=False)
        try:
            svc.should_rate_limit(_req(domain="sleepy"))
        finally:
            svc._sleeper_semaphore.release()
        assert svc._time_source.sleeps == []
        store.flush()
        assert (
            sink.counters["ratelimit.service.call.should_rate_limit.sleep_shed"]
            == 1
        )

    def test_not_draining_still_sleeps(self, test_store):
        store, _ = test_store
        svc, cache = _service(
            store,
            files={"config.sleepy": SLEEPY_YAML},
            max_sleeping_routines=2,
            draining_probe=lambda: False,
        )
        cache.next_throttle = 1500
        svc.should_rate_limit(_req(domain="sleepy"))
        assert svc._time_source.sleeps == [1.5]


# -- slab watermarks ---------------------------------------------------------


def _engine(ts, **kw):
    kw.setdefault("n_slots", 1 << 10)
    kw.setdefault("buckets", (128, 1024))
    kw.setdefault("max_batch", 1024)
    kw.setdefault("use_pallas", False)
    return SlabDeviceEngine(time_source=ts, **kw)


def _fill(engine, n, divider=60, jitter=300):
    # structured fingerprints with pairwise-distinct (set, way-preference)
    # under the default geometry (1024 slots / 128 ways = 8 sets): fp_lo
    # walks the sets, fp_hi bits [7, 14) (the rotation source,
    # ops/slab.py _choose_ways) walk the ways within each set — so a
    # ONE-batch fill deterministically creates n live rows instead of
    # dropping a handful to in-batch way contention
    items = [
        _Item(
            fp=((((i + 1) >> 3) << 39) | (i + 1)),
            hits=1,
            limit=1000,
            divider=divider,
            jitter=jitter,
        )
        for i in range(n)
    ]
    engine.submit(items)


class TestSlabWatermarks:
    def test_high_watermark_is_pure_observability(self):
        """The pressure watermark raises the degraded probe and NOTHING
        else: no sweep pass, no admission shed — the set-associative scan
        absorbs pressure by evicting least-valuable ways in-kernel."""
        ts = FakeTimeSource(1_000_000)
        engine = _engine(ts, watermark_high=0.05)
        _fill(engine, 100)  # occupancy ~0.098 >= 0.05
        snap = engine.health_snapshot()
        assert snap["watermark"] == 1
        assert snap["live_slots"] == 100
        assert "sweeps" not in snap  # the stop-the-world sweep is gone
        assert "pressure" in engine.watermark_reason()
        # rows stay TTL-pinned past their window end — nothing reclaims
        # them eagerly; the eviction scan reuses them lazily, per insert
        ts.advance(120)
        snap = engine.health_snapshot()
        assert snap["live_slots"] == 100
        assert snap["watermark"] == 1
        # TTL (window 60s + jitter 300s) passes: occupancy drains itself
        ts.advance(300)
        snap = engine.health_snapshot()
        assert snap["live_slots"] == 0
        assert snap["watermark"] == 0
        assert engine.watermark_reason() is None

    def test_full_occupancy_never_sheds_admission(self):
        """The old critical-watermark cliff is gone: at (and past) 100%
        live occupancy every submit still answers — colliding inserts
        evict the least-valuable way in-kernel, and the eviction mix is
        the only signal pressure emits."""
        ts = FakeTimeSource(1_000_000)
        # 128 slots = exactly one 128-way set: wave A fills it completely
        engine = _engine(ts, n_slots=128, buckets=(128,), max_batch=128)
        for i in range(128):
            assert engine.submit(
                [_Item(fp=i + 1, hits=1, limit=1000, divider=60, jitter=300)]
            ) == [1]
        snap = engine.health_snapshot()
        assert snap["live_slots"] == 128
        assert snap["occupancy"] == 1.0
        # wave B: 64 NEW keys against the full set — each submit answers
        # (count restarts at 1, the fail-open posture) by evicting a live
        # way, and every displacement is counted, never silent
        for i in range(64):
            assert engine.submit(
                [_Item(fp=1000 + i, hits=1, limit=1000, divider=60, jitter=300)]
            ) == [1]
        snap = engine.health_snapshot()
        assert snap["occupancy"] == 1.0  # still full, still serving
        assert snap["evictions_live"] == 64
        assert snap["watermark"] == 0  # no watermark configured: no alarm

    def test_watermarks_off_by_default(self):
        ts = FakeTimeSource(1_000_000)
        engine = _engine(ts)
        _fill(engine, 100)
        snap = engine.health_snapshot()
        assert snap["watermark"] == 0
        assert engine.watermark_reason() is None

    def test_critical_watermark_kwarg_is_gone(self):
        """The shed path is deleted, not deprecated-but-alive: the engine
        no longer even accepts the knob (settings translate a configured
        SLAB_WATERMARK_CRITICAL into a boot-time deprecation warning)."""
        ts = FakeTimeSource(1_000_000)
        with pytest.raises(TypeError):
            _engine(ts, watermark_high=0.9, watermark_critical=0.95)


# -- settings ----------------------------------------------------------------


class TestOverloadSettings:
    def test_env_parsing(self):
        from api_ratelimit_tpu.settings import new_settings

        s = new_settings(
            {
                "OVERLOAD_SHED_MODE": "deny",
                "OVERLOAD_MAX_QUEUE": "8192",
                "OVERLOAD_BROWNOUT_TARGET_MS": "5.5",
                "OVERLOAD_BROWNOUT_EXIT_MS": "2",
                "OVERLOAD_EWMA_ALPHA": "0.5",
                "OVERLOAD_DEADLINE_PROPAGATION": "false",
                "SLAB_WATERMARK_HIGH": "0.85",
            }
        )
        assert s.shed_mode() == "deny"
        assert s.overload_max_queue == 8192
        assert s.overload_brownout_target_ms == 5.5
        assert s.overload_brownout_exit_ms == 2.0
        assert s.overload_ewma_alpha == 0.5
        assert s.overload_deadline_propagation is False
        assert s.slab_watermark() == 0.85

    def test_defaults_are_inert(self):
        from api_ratelimit_tpu.settings import new_settings

        s = new_settings({})
        assert s.shed_mode() == SHED_MODE_UNAVAILABLE
        assert s.overload_max_queue == 0
        assert s.overload_brownout_target_ms == 0.0
        assert s.overload_deadline_propagation is True
        assert s.slab_watermark() == 0.0

    def test_junk_shed_mode_fails_boot(self):
        from api_ratelimit_tpu.settings import new_settings

        s = new_settings({"OVERLOAD_SHED_MODE": "yolo"})
        with pytest.raises(ValueError, match="OVERLOAD_SHED_MODE"):
            s.shed_mode()

    def test_junk_watermarks_fail_boot(self):
        from api_ratelimit_tpu.settings import new_settings

        with pytest.raises(ValueError, match="SLAB_WATERMARK"):
            new_settings({"SLAB_WATERMARK_HIGH": "1.5"}).slab_watermark()

    def test_critical_watermark_deprecated_not_fatal(self, caplog):
        """An old deployment config carrying SLAB_WATERMARK_CRITICAL (even
        one the old validator would have rejected as misordered) keeps
        booting: the knob is accepted-and-ignored with one warning line."""
        import logging

        from api_ratelimit_tpu.settings import new_settings

        s = new_settings(
            {"SLAB_WATERMARK_HIGH": "0.9", "SLAB_WATERMARK_CRITICAL": "0.5"}
        )
        assert s.slab_watermark() == 0.9  # no ordering validation, no raise
        log = logging.getLogger("test.deprecations")
        with caplog.at_level(logging.WARNING):
            s.warn_deprecated_knobs(log)
        assert any(
            "SLAB_WATERMARK_CRITICAL is deprecated" in r.message
            for r in caplog.records
        )
        # unset: silent
        caplog.clear()
        with caplog.at_level(logging.WARNING):
            new_settings({}).warn_deprecated_knobs(log)
        assert not caplog.records

    def test_queue_full_fault_kind_parses(self):
        rules = parse_fault_spec("batcher.submit:queue_full:0.5")
        assert rules[0].kind == "queue_full"
        with pytest.raises(ValueError, match="probability"):
            parse_fault_spec("batcher.submit:queue_full:2.0")


# -- full stack over real gRPC -----------------------------------------------


class TestFullStackOverload:
    """The acceptance scenario: batcher stalled/filled via fault injection,
    requests past the watermark answered within their deadline by the
    configured posture, with overload stats + degraded healthcheck body."""

    def _boot(self, tmp_path, **settings_kw):
        from api_ratelimit_tpu.runner import Runner
        from api_ratelimit_tpu.settings import Settings

        config_dir = tmp_path / "current" / "rl" / "config"
        config_dir.mkdir(parents=True, exist_ok=True)
        (config_dir / "c.yaml").write_text(
            "domain: overload\n"
            "descriptors:\n"
            "  - key: one\n"
            "    rate_limit: {unit: minute, requests_per_unit: 100}\n"
            "  - key: sleepy\n"
            "    rate_limit: {unit: minute, requests_per_unit: 1}\n"
            "    sleep_on_throttle: true\n"
        )
        settings = Settings(
            port=0,
            grpc_port=0,
            debug_port=0,
            use_statsd=False,
            runtime_path=str(tmp_path / "current"),
            runtime_subdirectory="rl",
            backend_type="tpu",
            tpu_slab_slots=1 << 12,
            tpu_use_pallas=False,
            expiration_jitter_max_seconds=0,
            log_level="ERROR",
            **settings_kw,
        )
        runner = Runner(settings, sink=TestSink())
        runner.run_background()
        assert runner.wait_ready(10.0)
        return runner

    def _grpc_request(self, key="one"):
        from api_ratelimit_tpu.pb import rls_v3

        request = rls_v3.RateLimitRequest(domain="overload")
        d = request.descriptors.add()
        d.entries.add(key=key, value="x")
        return request

    def _healthcheck(self, runner):
        import urllib.request

        with urllib.request.urlopen(
            f"http://localhost:{runner.server.http_port}/healthcheck",
            timeout=5,
        ) as resp:
            return resp.status, resp.read().decode()

    def test_queue_full_shed_allow_posture(self, tmp_path):
        import grpc

        from api_ratelimit_tpu.pb import rls_grpc, rls_v3

        runner = self._boot(
            tmp_path,
            overload_shed_mode="allow",
            fault_inject="batcher.submit:queue_full:1.0",
        )
        try:
            with grpc.insecure_channel(
                f"localhost:{runner.server.grpc_port}"
            ) as ch:
                stub = rls_grpc.RateLimitServiceV3Stub(ch)
                t0 = time.monotonic()
                responses = [
                    stub.ShouldRateLimit(self._grpc_request(), timeout=5.0)
                    for _ in range(3)
                ]
                elapsed = time.monotonic() - t0
            # every shed answered OK, within the deadline, carrying the
            # shed header
            assert elapsed < 5.0
            for resp in responses:
                assert resp.overall_code == rls_v3.RateLimitResponse.OK
                assert any(
                    h.key == "x-ratelimit-shed"
                    for h in resp.response_headers_to_add
                )
            snap = runner.stats_store.debug_snapshot()
            assert snap["ratelimit.overload.shed"] == 3
            assert snap["ratelimit.overload.queue_full"] == 3
            assert snap["ratelimit.overload.shedding"] == 1
            status, body = self._healthcheck(runner)
            assert status == 200 and "overload" in body
            # chaos ends: traffic admits normally, shed state clears
            runner.fault_injector.clear()
            with grpc.insecure_channel(
                f"localhost:{runner.server.grpc_port}"
            ) as ch:
                stub = rls_grpc.RateLimitServiceV3Stub(ch)
                resp = stub.ShouldRateLimit(self._grpc_request(), timeout=5.0)
            assert resp.overall_code == rls_v3.RateLimitResponse.OK
            assert not resp.response_headers_to_add
            status, body = self._healthcheck(runner)
            assert (status, body) == (200, "OK")
        finally:
            runner.stop()

    def test_queue_full_shed_unavailable_posture(self, tmp_path):
        import grpc

        from api_ratelimit_tpu.pb import rls_grpc

        runner = self._boot(
            tmp_path,
            overload_shed_mode="unavailable",
            fault_inject="batcher.submit:queue_full:1.0",
        )
        try:
            with grpc.insecure_channel(
                f"localhost:{runner.server.grpc_port}"
            ) as ch:
                stub = rls_grpc.RateLimitServiceV3Stub(ch)
                with pytest.raises(grpc.RpcError) as err:
                    stub.ShouldRateLimit(self._grpc_request(), timeout=5.0)
            # UNAVAILABLE: the Envoy-retriable shed class
            assert err.value.code() == grpc.StatusCode.UNAVAILABLE
            snap = runner.stats_store.debug_snapshot()
            assert snap["ratelimit.overload.shed"] == 1
        finally:
            runner.stop()

    def test_queue_full_shed_deny_posture(self, tmp_path):
        import grpc

        from api_ratelimit_tpu.pb import rls_grpc, rls_v3

        runner = self._boot(
            tmp_path,
            overload_shed_mode="deny",
            fault_inject="batcher.submit:queue_full:1.0",
        )
        try:
            with grpc.insecure_channel(
                f"localhost:{runner.server.grpc_port}"
            ) as ch:
                stub = rls_grpc.RateLimitServiceV3Stub(ch)
                resp = stub.ShouldRateLimit(self._grpc_request(), timeout=5.0)
            assert resp.overall_code == rls_v3.RateLimitResponse.OVER_LIMIT
        finally:
            runner.stop()

    def test_deadline_exceeded_full_stack(self, tmp_path):
        """A stalled batcher (injected delay) + a short client deadline:
        the request resolves as DEADLINE_EXCEEDED quickly and the drop is
        counted — never a late answer, never an unbounded wait."""
        import grpc

        from api_ratelimit_tpu.pb import rls_grpc

        runner = self._boot(
            tmp_path, fault_inject="batcher.submit:delay_ms:400"
        )
        try:
            with grpc.insecure_channel(
                f"localhost:{runner.server.grpc_port}"
            ) as ch:
                stub = rls_grpc.RateLimitServiceV3Stub(ch)
                t0 = time.monotonic()
                with pytest.raises(grpc.RpcError) as err:
                    stub.ShouldRateLimit(self._grpc_request(), timeout=0.15)
                elapsed = time.monotonic() - t0
            assert err.value.code() == grpc.StatusCode.DEADLINE_EXCEEDED
            assert elapsed < 5.0
            # the server-side drop lands slightly after the client timeout
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                snap = runner.stats_store.debug_snapshot()
                if snap.get("ratelimit.overload.deadline_expired", 0) >= 1:
                    break
                time.sleep(0.05)
            assert snap["ratelimit.overload.deadline_expired"] >= 1
        finally:
            runner.stop()

    def test_drain_under_load_sheds_sleep(self, tmp_path):
        """Drain-under-load: once health flips for shutdown, a
        sleep_on_throttle request returns immediately (sleep_shed) instead
        of pinning a worker for the pacing sleep."""
        import grpc

        from api_ratelimit_tpu.pb import rls_grpc

        runner = self._boot(tmp_path, max_sleeping_routines=4)
        try:
            with grpc.insecure_channel(
                f"localhost:{runner.server.grpc_port}"
            ) as ch:
                stub = rls_grpc.RateLimitServiceV3Stub(ch)
                # drain: health goes NOT_SERVING, but in-flight/straggler
                # traffic is still answered — without the pacing sleep
                runner.server.health.fail()
                t0 = time.monotonic()
                stub.ShouldRateLimit(
                    self._grpc_request(key="sleepy"), timeout=10.0
                )
                elapsed = time.monotonic() - t0
            assert elapsed < 5.0  # limit 1/min: an un-shed sleep is >> this
            snap = runner.stats_store.debug_snapshot()
            assert (
                snap["ratelimit.service.call.should_rate_limit.sleep_shed"]
                >= 1
            )
        finally:
            runner.stop()


# -- DISPATCH_LOOP both-arms parity -------------------------------------------


class TestDispatchLoopOverloadParity:
    """The dispatch loop (backends/dispatch.py) and the leader-collects
    batcher are interchangeable arms of the same admission contract:
    expired work is dropped at (ring) take time before packing, the shared
    batcher.submit chaos site sheds identically, and every shed posture
    answers the same wire response under DISPATCH_LOOP on/off."""

    @staticmethod
    def _real_cache(store, dispatch_loop, **kw):
        from api_ratelimit_tpu.backends.tpu import TpuRateLimitCache
        from api_ratelimit_tpu.limiter.base_limiter import BaseRateLimiter

        base = BaseRateLimiter(FakeTimeSource(1_000_000), near_limit_ratio=0.8)
        return TpuRateLimitCache(
            base,
            n_slots=1 << 12,
            batch_window_seconds=0.002,
            buckets=(8, 128),
            max_batch=128,
            use_pallas=False,
            stats_scope=store.scope("ratelimit"),
            dispatch_loop=dispatch_loop,
            **kw,
        )

    @pytest.mark.parametrize("arm", [True, False])
    def test_expired_dropped_at_take_before_packing(self, arm, test_store):
        store, _ = test_store
        cache = self._real_cache(store, arm)
        engine = cache.engine
        assert (engine._dispatch is not None) == arm
        import numpy as np

        block = np.zeros((6, 1), dtype=np.uint32)
        block[0] = 42
        block[2] = 1
        block[3] = 10
        block[4] = 60
        try:
            with deadline_scope(-0.001):
                with pytest.raises(DeadlineExceededError):
                    engine.submit_rows(np.array(block))
            # dropped BEFORE packing: the device never saw a decision
            assert engine.health_snapshot()["decisions"] == 0
            drops = (
                engine._dispatch.deadline_drops
                if arm
                else engine._batcher.deadline_drops
            )
            assert drops == 1
            # a fresh submit on the same arm still works
            assert engine.submit_rows(np.array(block)).tolist() == [1]
        finally:
            cache.close()

    @pytest.mark.parametrize("arm", [True, False])
    @pytest.mark.parametrize(
        "mode", [SHED_MODE_ALLOW, SHED_MODE_DENY, SHED_MODE_UNAVAILABLE]
    )
    def test_shed_postures_answer_identically(self, arm, mode, test_store):
        """queue_full injected at the SHARED batcher.submit site: the
        service's posture answer must be byte-for-byte the same whichever
        arm is live."""
        store, sink = test_store
        controller = AdmissionController(
            shed_mode=mode, scope=store.scope("ratelimit")
        )
        injector = FaultInjector.from_spec("batcher.submit:queue_full:1")
        cache = self._real_cache(
            store, arm, overload=controller, fault_injector=injector
        )
        svc = RateLimitService(
            runtime=_FakeRuntime({"config.ov": OVERLOAD_YAML}),
            cache=cache,
            stats_scope=store.scope("ratelimit").scope("service"),
            time_source=FakeTimeSource(1_000_000),
            overload=controller,
        )
        try:
            if mode == SHED_MODE_UNAVAILABLE:
                with pytest.raises(QueueFullError):
                    svc.should_rate_limit(_req())
            else:
                overall, statuses, headers = svc.should_rate_limit(_req())
                if mode == SHED_MODE_ALLOW:
                    assert overall == Code.OK
                    assert statuses[0].code == Code.OK
                    assert any(
                        h.key == "x-ratelimit-shed" and h.value == "queue_full"
                        for h in headers
                    )
                else:
                    assert overall == Code.OVER_LIMIT
                    assert statuses[0].code == Code.OVER_LIMIT
            store.flush()
            assert sink.counters["ratelimit.overload.shed"] == 1
            assert sink.counters["ratelimit.overload.queue_full"] == 1
        finally:
            cache.close()

    @pytest.mark.parametrize("arm", [True, False])
    def test_brownout_sheds_identically(self, arm, test_store):
        store, _ = test_store
        controller = AdmissionController(
            shed_mode=SHED_MODE_UNAVAILABLE,
            brownout_target_ms=1.0,
            ewma_alpha=1.0,
            scope=store.scope("ratelimit"),
        )
        cache = self._real_cache(store, arm, overload=controller)
        engine = cache.engine
        import numpy as np

        block = np.zeros((6, 1), dtype=np.uint32)
        block[0] = 7
        block[2] = 1
        block[3] = 10
        block[4] = 60
        try:
            assert engine.submit_rows(np.array(block)).tolist() == [1]
            _brownout(controller)
            with pytest.raises(BrownoutError):
                engine.submit_rows(np.array(block))
        finally:
            cache.close()
