"""Pallas decide kernel parity vs the jnp reference implementation
(interpret mode on CPU; the same comparison runs on real TPU hardware via
scripts in bench/verify flows)."""

import numpy as np
import jax.numpy as jnp

from api_ratelimit_tpu.ops.decide import decide
from api_ratelimit_tpu.ops.pallas_decide import pallas_decide


def test_pallas_decide_matches_jnp():
    rng = np.random.default_rng(3)
    b = 2048
    limit = rng.integers(1, 100, size=b).astype(np.uint32)
    hits = rng.integers(0, 5, size=b).astype(np.uint32)  # zeros = padding
    before = rng.integers(0, 120, size=b).astype(np.uint32)
    after = before + hits
    divider = rng.choice([1, 60, 3600, 86400], size=b).astype(np.int32)
    divider[hits == 0] = 0  # padding rows carry zeroed metadata
    now = 1_722_300_000

    args = (
        jnp.asarray(before),
        jnp.asarray(after),
        jnp.asarray(hits),
        jnp.asarray(limit),
        jnp.asarray(divider),
        jnp.int32(now),
        jnp.float32(0.8),
    )
    ref = decide(*args)
    got = pallas_decide(*args, interpret=True)

    for name in ref._fields:
        r = np.asarray(getattr(ref, name))
        g = np.asarray(getattr(got, name))
        mismatch = np.nonzero(r != g)[0]
        assert mismatch.size == 0, (
            f"{name} mismatch at {mismatch[:5]}: ref={r[mismatch[:5]]} got={g[mismatch[:5]]}"
        )
