"""Differential parity: the fused Pallas INCRBY kernel (ops/pallas_slab.py,
interpret mode) must match the XLA update path bit-for-bit — state evolution,
before/after counters, scatter contents, and the fused decision — over
randomized multi-step streams with duplicates, window rollovers, in-batch
slot collisions, and padding. This certifies the kernel against the same
oracle chain that already certifies the XLA path (test_slab.py), so passing
here means the kernel inherits every pinned reference semantic."""

import numpy as np
import pytest

import jax.numpy as jnp

from api_ratelimit_tpu.ops.slab import (
    SlabBatch,
    _slab_step_sorted,
    _slab_update_sorted,
    _unsort,
    make_slab,
)

N_SLOTS = 1 << 10


def random_batch(rng, b, n_keys, now_unused=None):
    """Zipf-ish duplicated keys, mixed units, some padding at the tail."""
    key = rng.randint(0, n_keys, b).astype(np.uint64)
    fp = key * np.uint64(0x9E3779B185EBCA87) + np.uint64(1)  # nonzero fps
    hits = rng.randint(1, 4, b).astype(np.uint32)
    n_pad = rng.randint(0, b // 4)
    if n_pad:
        hits[b - n_pad :] = 0
    limit = rng.choice([3, 10, 100], b).astype(np.uint32)
    divider = rng.choice([1, 60, 3600], b).astype(np.int32)
    jitter = rng.randint(0, 30, b).astype(np.int32)
    return SlabBatch(
        fp_lo=jnp.asarray((fp & np.uint64(0xFFFFFFFF)).astype(np.uint32)),
        fp_hi=jnp.asarray((fp >> np.uint64(32)).astype(np.uint32)),
        hits=jnp.asarray(hits),
        limit=jnp.asarray(limit),
        divider=jnp.asarray(divider),
        jitter=jnp.asarray(jitter),
    )


def run_update_matches_xla_over_stream(interpret: bool):
    """Same seed, two engines: XLA math vs the Pallas kernel. The whole
    table must stay equal after every step (scatter contents included),
    and each step's sorted before/after must agree exactly."""
    rng = np.random.RandomState(7)
    state_x = make_slab(N_SLOTS)
    state_p = make_slab(N_SLOTS)
    now = 1_000_000
    for step in range(8):
        batch = random_batch(rng, 512, n_keys=64)
        now += rng.randint(0, 3)
        state_x, bx, ax, _, ox, hx, _ = _slab_update_sorted(
            state_x, batch, jnp.int32(now), ways=128
        )
        state_p, bp, ap, _, op_, hp, _ = _slab_update_sorted(
            state_p,
            batch,
            jnp.int32(now),
            ways=128,
            use_pallas=True,
            interpret=interpret,
        )
        assert np.array_equal(np.asarray(bx), np.asarray(bp)), f"before step {step}"
        assert np.array_equal(np.asarray(ax), np.asarray(ap)), f"after step {step}"
        assert np.array_equal(np.asarray(ox), np.asarray(op_))
        assert np.array_equal(np.asarray(hx), np.asarray(hp)), f"health step {step}"
        assert np.array_equal(
            np.asarray(state_x.table), np.asarray(state_p.table)
        ), f"table diverged at step {step}"


def run_fused_decide_matches_xla_decide(interpret: bool):
    """use_pallas=True through _slab_step_sorted fuses the decision into
    the kernel; every decision field must equal the jnp decide() twin."""
    rng = np.random.RandomState(11)
    state_x = make_slab(N_SLOTS)
    state_p = make_slab(N_SLOTS)
    now = 5_000_000
    for step in range(6):
        batch = random_batch(rng, 256, n_keys=24)
        now += rng.randint(0, 2)
        state_x, _, _, dx, ox, _ = _slab_step_sorted(
            state_x,
            batch,
            jnp.int32(now),
            jnp.float32(0.8),
            ways=128,
            use_pallas=False,
        )
        state_p, _, _, dp, op_, _ = _slab_step_sorted(
            state_p,
            batch,
            jnp.int32(now),
            jnp.float32(0.8),
            ways=128,
            use_pallas=True,
            interpret=interpret,
        )
        for field in dx._fields:
            got = np.asarray(_unsort(getattr(dp, field), op_))
            want = np.asarray(_unsort(getattr(dx, field), ox))
            assert np.array_equal(got, want), f"{field} step {step}"


def run_lean_decide_matches_full(interpret: bool):
    """lean_decide (decided-mode fire-and-forget): the kernel writes only
    the code tile, which must equal the full kernel's code and the XLA
    twin's, with identical state evolution."""
    rng = np.random.RandomState(23)
    state_x = make_slab(N_SLOTS)
    state_l = make_slab(N_SLOTS)
    now = 2_000_000
    for step in range(5):
        batch = random_batch(rng, 384, n_keys=32)
        now += rng.randint(0, 2)
        state_x, _, _, dx, ox, hx = _slab_step_sorted(
            state_x,
            batch,
            jnp.int32(now),
            jnp.float32(0.8),
            ways=128,
            use_pallas=False,
        )
        state_l, _, _, dl, ol, hl = _slab_step_sorted(
            state_l,
            batch,
            jnp.int32(now),
            jnp.float32(0.8),
            ways=128,
            use_pallas=True,
            lean_decide=True,
            interpret=interpret,
        )
        got = np.asarray(_unsort(dl.code, ol))
        want = np.asarray(_unsort(dx.code, ox))
        assert np.array_equal(got, want), f"code step {step}"
        assert np.array_equal(np.asarray(hx), np.asarray(hl))
        assert np.array_equal(
            np.asarray(state_x.table), np.asarray(state_l.table)
        ), f"table diverged at step {step}"


def test_lean_decide_matches_full():
    run_lean_decide_matches_full(interpret=True)


def test_kernel_rejects_bad_shapes():
    from api_ratelimit_tpu.ops.pallas_slab import pallas_slab_apply

    z = jnp.zeros(100, jnp.uint32)  # not a multiple of 128
    with pytest.raises(ValueError, match="multiple of 128"):
        pallas_slab_apply(
            z, z, z, z,
            z.astype(jnp.int32), z.astype(jnp.int32),
            jnp.zeros(100, bool),
            jnp.zeros((5, 100), jnp.uint32),
            jnp.int32(0), jnp.float32(0.8),
            interpret=True,
        )


def run_in_batch_slot_collision_parity(interpret: bool):
    """Two distinct keys forced into one way in one batch (the documented
    contention-drop case): the pallas path must pick the same winner and
    count the same drop."""
    # a tiny 4-set x 1-way table where the set-index split (fp_lo mod 4)
    # aliases every key into one set
    state_x = make_slab(4)
    state_p = make_slab(4)
    fps = (5, 9, 13, 21, 37)  # distinct keys, all land in set 1 mod 4
    b = 128  # kernel tile width; tail is hits=0 padding
    fp_lo = np.zeros(b, np.uint32)
    hits = np.zeros(b, np.uint32)
    fp_lo[: len(fps)] = fps
    hits[: len(fps)] = 1
    batch = SlabBatch(
        fp_lo=jnp.asarray(fp_lo),
        fp_hi=jnp.asarray(np.full(b, 1, np.uint32)),
        hits=jnp.asarray(hits),
        limit=jnp.asarray(np.full(b, 10, np.uint32)),
        divider=jnp.asarray(np.full(b, 60, np.int32)),
        jitter=jnp.asarray(np.zeros(b, np.int32)),
    )
    now = jnp.int32(1000)
    state_x, bx, ax, _, ox, hx, _ = _slab_update_sorted(state_x, batch, now, 1)
    state_p, bp, ap, _, op_, hp, _ = _slab_update_sorted(
        state_p, batch, now, 1, use_pallas=True, interpret=interpret
    )
    assert np.array_equal(np.asarray(state_x.table), np.asarray(state_p.table))
    assert np.array_equal(np.asarray(bx), np.asarray(bp))
    assert np.array_equal(np.asarray(hx), np.asarray(hp))


def test_multi_grid_step_carries():
    """A batch spanning several kernel grid steps (BLOCK_ROWS x 128 items
    each): the SMEM-carried running totals (hits cumsum, segment-base max)
    must hand off across step boundaries exactly — compared against the
    XLA twin on the full table, counters, and health."""
    from api_ratelimit_tpu.ops.pallas_slab import BLOCK_ROWS, LANES

    b = 2 * BLOCK_ROWS * LANES  # exactly 2 grid steps
    rng = np.random.RandomState(3)
    key = rng.randint(0, 2000, b).astype(np.uint64)
    fp = key * np.uint64(0x9E3779B185EBCA87) + np.uint64(1)
    hits = rng.randint(1, 3, b).astype(np.uint32)
    hits[-64:] = 0  # padding tail
    batch = SlabBatch(
        fp_lo=jnp.asarray((fp & np.uint64(0xFFFFFFFF)).astype(np.uint32)),
        fp_hi=jnp.asarray((fp >> np.uint64(32)).astype(np.uint32)),
        hits=jnp.asarray(hits),
        limit=jnp.asarray(np.full(b, 50, np.uint32)),
        divider=jnp.asarray(np.full(b, 60, np.int32)),
        jitter=jnp.asarray(np.zeros(b, np.int32)),
    )
    now = jnp.int32(1_000_000)
    state_x = make_slab(1 << 14)
    state_p = make_slab(1 << 14)
    state_x, bx, ax, _, _, hx, _ = _slab_update_sorted(state_x, batch, now, 128)
    state_p, bp, ap, _, _, hp, _ = _slab_update_sorted(
        state_p, batch, now, 128, use_pallas=True, interpret=True
    )
    assert np.array_equal(np.asarray(bx), np.asarray(bp))
    assert np.array_equal(np.asarray(ax), np.asarray(ap))
    assert np.array_equal(np.asarray(hx), np.asarray(hp))
    assert np.array_equal(np.asarray(state_x.table), np.asarray(state_p.table))


def test_update_matches_xla_over_stream():
    run_update_matches_xla_over_stream(interpret=True)


def run_eviction_pressure_parity(interpret: bool):
    """>100% occupancy stream on a 2-set x 128-way table: every batch
    forces in-kernel evictions across all three tiers, and the pallas
    way-scan (ops/pallas_slab.py pallas_way_scan) must pick bit-identical
    victims — table, counters, and the eviction-mix health vector all
    equal the XLA scan's."""
    rng = np.random.RandomState(31)
    state_x = make_slab(256)  # 2 sets of 128 ways
    state_p = make_slab(256)
    now = 3_000_000
    for step in range(6):
        batch = random_batch(rng, 512, n_keys=700)  # keys ~2.7x capacity
        now += rng.randint(0, 40)  # let TTLs expire between steps
        state_x, bx, ax, _, ox, hx, _ = _slab_update_sorted(
            state_x, batch, jnp.int32(now), ways=128
        )
        state_p, bp, ap, _, op_, hp, _ = _slab_update_sorted(
            state_p,
            batch,
            jnp.int32(now),
            ways=128,
            use_pallas=True,
            interpret=interpret,
        )
        assert np.array_equal(np.asarray(bx), np.asarray(bp)), f"step {step}"
        assert np.array_equal(np.asarray(ax), np.asarray(ap)), f"step {step}"
        assert np.array_equal(np.asarray(hx), np.asarray(hp)), f"health {step}"
        assert np.array_equal(
            np.asarray(state_x.table), np.asarray(state_p.table)
        ), f"table diverged at step {step}"
    # the pressure stream actually evicted (all three classes exercised
    # over the run — otherwise this test proves nothing)
    assert int(np.asarray(hx)[2]) > 0 or int(np.asarray(hx)[0]) > 0


def test_eviction_pressure_parity():
    run_eviction_pressure_parity(interpret=True)


def test_fused_decide_matches_xla_decide():
    run_fused_decide_matches_xla_decide(interpret=True)


def test_in_batch_slot_collision_parity():
    run_in_batch_slot_collision_parity(interpret=True)
