"""On-hardware Pallas certification (VERDICT r3 #4): the same differential
suite that pins the kernel against the XLA path in interpret mode
(test_pallas_slab.py) re-runs with the kernel COMPILED through Mosaic on a
real TPU, so a lowering bug can never hide behind the interpreter.

Run on a chip-attached host:

    TPU_TESTS=1 python -m pytest tests/test_pallas_tpu.py -v

(TPU_TESTS=1 makes conftest.py leave the real platform visible instead of
forcing the virtual CPU mesh; run only this module under that env — see
conftest.py. `make tests_tpu` wraps this.)

Skips cleanly when no TPU is attached, so it is safe in every suite run.
"""

from __future__ import annotations

import os

import pytest

pytestmark = pytest.mark.tpu

if os.environ.get("TPU_TESTS", "") != "1":
    pytest.skip(
        "on-chip suite: set TPU_TESTS=1 on a chip-attached host",
        allow_module_level=True,
    )

import jax  # noqa: E402

if jax.devices()[0].platform != "tpu":
    pytest.skip(
        f"TPU_TESTS=1 but jax sees {jax.devices()[0].platform!r}, not tpu",
        allow_module_level=True,
    )

# tests/ has no __init__.py: pytest's prepend import mode puts this dir on
# sys.path, so the sibling module imports by its bare name
from test_pallas_slab import (  # noqa: E402
    run_fused_decide_matches_xla_decide,
    run_in_batch_slot_collision_parity,
    run_lean_decide_matches_full,
    run_update_matches_xla_over_stream,
)


def test_update_matches_xla_on_chip():
    run_update_matches_xla_over_stream(interpret=False)


def test_fused_decide_matches_xla_on_chip():
    run_fused_decide_matches_xla_decide(interpret=False)


def test_lean_decide_on_chip():
    run_lean_decide_matches_full(interpret=False)


def test_in_batch_slot_collision_on_chip():
    run_in_batch_slot_collision_parity(interpret=False)


def test_floor_div_exact_on_chip():
    """The exact floor division under every device path (window starts,
    throttle pacing — ops/decide.py) contains no divide at all: it is a
    Newton-reciprocal built from mul/sub/bitcast, and its exactness
    depends on the chip's f32 multiply/rounding staying within the +-1
    band the integer fixup corrects. CPU tests pin the formula; this pins
    the hardware semantics (both XLA and Pallas paths share the helper,
    so on-chip parity tests alone cannot catch a TPU-specific f32
    multiply deviation)."""
    import numpy as np
    import jax.numpy as jnp

    from api_ratelimit_tpu.ops.decide import floor_div_exact_i32

    rng = np.random.RandomState(3)
    a = rng.randint(0, 2**31, size=1 << 16).astype(np.int32)
    b = rng.randint(1, 2**31, size=1 << 16).astype(np.int32)
    b[::2] = rng.choice([1, 60, 3600, 86400], size=(1 << 15)).astype(np.int32)
    # adversarial: quotients near exact multiples, max dividend
    a[:4] = [2**31 - 1, 2**31 - 1, 86400 * 19676 - 1, 86400 * 19676]
    b[:4] = [1, 3, 86400, 86400]
    got = np.asarray(jax.jit(floor_div_exact_i32)(jnp.asarray(a), jnp.asarray(b)))
    want = (a.astype(np.int64) // b.astype(np.int64)).astype(np.int32)
    np.testing.assert_array_equal(got, want)


def test_packbits_muladd_on_chip():
    """Hardware parity pin for the multiply-add packbits twin
    (ops/decide.py packbits_muladd — the candidate swap if attribution shows
    packbits' shift/or lowering is pathological, like division was). The
    formula is pinned on CPU in tests/test_slab.py; this pins the chip's
    u32 multiply-add reduce lowering."""
    import numpy as np
    import jax.numpy as jnp

    from api_ratelimit_tpu.ops.decide import packbits_muladd

    rng = np.random.RandomState(17)
    for size in (128, 1 << 16, 1 << 20):
        mask = rng.rand(size) < 0.41
        got = np.asarray(jax.jit(packbits_muladd)(jnp.asarray(mask)))
        np.testing.assert_array_equal(got, np.packbits(mask))
