"""At-scale OVER_LIMIT parity: the slab engine vs the exact oracle under a
Zipfian stream at a load factor matching the BASELINE Zipf-10M config
(10M keys on a 2^23-slot slab ~= 1.2 keys/slot). Collision quality is a
correctness issue at this density (SURVEY.md §7): live-way evictions and
in-batch drops erode parity, and this test pins (a) a floor on agreement
and (b) the fail-open invariant — the slab must NEVER reject a request
the oracle would allow.

The full-size run (10M keys, measured on the real stream) lives in
bench.py's parity entry; this scaled twin keeps the same density so the
collision behavior it certifies transfers.
"""

from __future__ import annotations

import time

import functools

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from api_ratelimit_tpu.ops.slab import (  # noqa: E402
    HEALTH_DROPS,
    HEALTH_EVICT_EXPIRED,
    HEALTH_EVICT_LIVE,
    HEALTH_EVICT_WINDOW,
    SlabBatch,
    _slab_step_sorted,
    _unsort,
    make_slab,
)
from api_ratelimit_tpu.testing.oracle import occurrence_rank, parity_report  # noqa: E402

LIMIT = 20
BATCH = 1 << 12
N_BATCHES = 12
N_KEYS = 400_000
N_SLOTS = 1 << 15  # ~1.2x denser than keys-touched; matches 10M/2^23 stress
# pinned (not auto) so the parity bounds below certify ONE geometry — the
# CPU-suite default shape (ops/slab.py DEFAULT_WAYS_HOST); wider ways only
# collide less
WAYS = 4


def _fmix(x):
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    return x ^ (x >> 16)


@functools.partial(jax.jit, donate_argnames=("state",))
def _step(state, ids, now):
    batch = SlabBatch(
        fp_lo=_fmix(ids),
        fp_hi=_fmix(ids ^ jnp.uint32(0x9E3779B9)),
        hits=jnp.ones_like(ids),
        limit=jnp.full_like(ids, LIMIT),
        divider=jnp.full_like(ids, 3600).astype(jnp.int32),
        jitter=jnp.zeros_like(ids).astype(jnp.int32),
    )
    state, _b, _a, d, order, health = _slab_step_sorted(
        state, batch, now, jnp.float32(0.8), WAYS, False
    )
    return state, _unsort(d.code, order).astype(jnp.uint8), health


def test_zipf_parity_at_baseline_density():
    rng = np.random.RandomState(11)
    ids = (rng.zipf(1.1, size=BATCH * N_BATCHES).astype(np.uint64) % N_KEYS).astype(
        np.uint32
    )
    now = jnp.int32(int(time.time()))

    state = make_slab(N_SLOTS)
    codes = []
    evict_live = drops = 0
    for i in range(N_BATCHES):
        state, out, health = _step(state, jnp.asarray(ids[i * BATCH : (i + 1) * BATCH]), now)
        codes.append(np.asarray(out))
        h = [int(v) for v in np.asarray(health)]
        evict_live += h[HEALTH_EVICT_LIVE]
        drops += h[HEALTH_DROPS]
        # one shared 3600s window, zero jitter: nothing can expire or roll
        # a window mid-test, so every eviction must be of the lossy tier
        assert h[HEALTH_EVICT_EXPIRED] == 0 and h[HEALTH_EVICT_WINDOW] == 0

    report = parity_report(ids, np.concatenate(codes), LIMIT)
    # the fail-open invariant is absolute: losses may under-count, never over
    assert report["false_over"] == 0
    # the oracle must actually exercise the over-limit branch for this to
    # certify anything
    assert report["oracle_over_frac"] > 0.1
    # pinned floor at BASELINE density (observed ~0.999+; live evictions +
    # drops at this load cost well under 1%)
    assert report["agreement"] >= 0.995, (report, evict_live, drops)
    # Structural drift bound (VERDICT r4 weak #3): every false_ok must be
    # explained by a counted lossy event. Provable envelope: a dropped
    # write loses its `hits` (=1 here) counted hits, delaying that key's
    # over-limit transition by at most one request; a live eviction loses
    # at most the victim's accumulated count, delaying its threshold
    # re-crossing by at most LIMIT requests. Hence
    # false_ok <= drops + evict_live * LIMIT.
    assert report["false_ok"] <= drops + evict_live * LIMIT, (
        report,
        evict_live,
        drops,
    )
    # Observed behavior is far tighter: the set scan evicts the LOWEST
    # count live way, so the typical loss is a cold key's tiny counter —
    # pin the tight envelope too, so a regression that makes losses MORE
    # parity-costly per event fails even if counters also grow.
    assert report["false_ok"] <= drops + evict_live, (report, evict_live, drops)
    # Absolute lossy-event budget at this stress density (deterministic
    # for the seed): a tripling of live evictions or drops fails here
    # even with false_ok unchanged.
    loss_rate = (evict_live + drops) / ids.size
    assert loss_rate < 0.05, (evict_live, drops, loss_rate)


def test_oracle_occurrence_rank_is_exact():
    ids = np.array([5, 5, 7, 5, 7, 9, 5], dtype=np.uint32)
    assert occurrence_rank(ids).tolist() == [0, 1, 0, 2, 1, 0, 3]
