"""Warm-restart persistence: snapshot format, reconcile rules, snapshotter
lifecycle, engine export/import, chaos (fault-injected) rejection, and the
offline inspect CLI.

The durability contract under test: a valid snapshot restores live counters
exactly; ANY invalid snapshot (bad magic/version/CRC, torn payload, wrong
topology) is rejected and the slab boots cold — counted, logged, never a
crash. Every restore-time loss fails open (an undercount can only
under-enforce), matching the slab's documented lossy posture.
"""

import importlib.util
import json
import os
import sys
import zlib

import numpy as np
import pytest

from api_ratelimit_tpu.backends.tpu import SlabDeviceEngine, _Item
from api_ratelimit_tpu.persist.snapshot import (
    HEADER_SIZE,
    MAGIC,
    SNAPSHOT_VERSION,
    SnapshotError,
    load_snapshot,
    read_header,
    reconcile_rows,
    write_snapshot,
)
from api_ratelimit_tpu.persist.snapshotter import SlabSnapshotter, snapshot_paths
from api_ratelimit_tpu.stats import Store, TestSink
from api_ratelimit_tpu.testing.faults import FaultInjector
from api_ratelimit_tpu.utils import FakeTimeSource

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NOW = 1_700_000_000


def _table(n=64, rows=()):
    """A slab table with the given (slot, fp_lo, count, window, expire,
    divider) rows planted."""
    t = np.zeros((n, 8), dtype=np.uint32)
    for slot, fp_lo, count, window, expire, divider in rows:
        t[slot] = [fp_lo, fp_lo ^ 0xABCD, count, window, expire, divider, 0, 0]
    return t


def _row(slot, count=3, window=NOW - (NOW % 60), expire=NOW + 90, divider=60):
    return (slot, 0x1111 + slot, count, window, expire, divider)


class TestSnapshotFormat:
    def test_round_trip(self, tmp_path):
        table = _table(rows=[_row(3), _row(17, count=9)])
        path = str(tmp_path / "slab.snap")
        n = write_snapshot(path, table, created_at=NOW, shard_index=2,
                           shard_count=4)
        assert n == os.path.getsize(path) == HEADER_SIZE + table.nbytes
        header, got = load_snapshot(path)
        assert (header.version, header.created_at) == (SNAPSHOT_VERSION, NOW)
        assert (header.shard_index, header.shard_count) == (2, 4)
        assert (header.n_slots, header.row_width) == (64, 8)
        np.testing.assert_array_equal(got, table)

    def test_read_header_only(self, tmp_path):
        path = str(tmp_path / "slab.snap")
        write_snapshot(path, _table(), created_at=NOW)
        header = read_header(path)
        assert header.n_slots == 64
        assert header.payload_len == 64 * 8 * 4

    def test_atomic_write_leaves_no_temp(self, tmp_path):
        path = str(tmp_path / "slab.snap")
        write_snapshot(path, _table(), created_at=NOW)
        write_snapshot(path, _table(rows=[_row(1)]), created_at=NOW + 1)
        assert sorted(os.listdir(tmp_path)) == ["slab.snap"]
        assert read_header(path).created_at == NOW + 1

    def test_bad_magic_rejected(self, tmp_path):
        path = str(tmp_path / "slab.snap")
        write_snapshot(path, _table(), created_at=NOW)
        raw = bytearray(open(path, "rb").read())
        raw[:8] = b"NOTASNAP"
        open(path, "wb").write(bytes(raw))
        with pytest.raises(SnapshotError, match="magic"):
            load_snapshot(path)

    def test_version_mismatch_rejected(self, tmp_path):
        path = str(tmp_path / "slab.snap")
        write_snapshot(path, _table(), created_at=NOW)
        raw = bytearray(open(path, "rb").read())
        raw[8] = 99  # version field
        # re-stamp the header CRC so ONLY the version check can fire —
        # proving the version gate works even on an internally-consistent
        # future-format file
        import struct

        head = bytes(raw[:56])
        raw[56:60] = struct.pack("<I", zlib.crc32(head))
        open(path, "wb").write(bytes(raw))
        with pytest.raises(SnapshotError, match="version 99"):
            load_snapshot(path)

    def test_header_corruption_rejected(self, tmp_path):
        path = str(tmp_path / "slab.snap")
        write_snapshot(path, _table(), created_at=NOW)
        raw = bytearray(open(path, "rb").read())
        raw[20] ^= 0xFF  # inside created_at
        open(path, "wb").write(bytes(raw))
        with pytest.raises(SnapshotError, match="header CRC"):
            load_snapshot(path)

    def test_payload_corruption_rejected(self, tmp_path):
        path = str(tmp_path / "slab.snap")
        write_snapshot(path, _table(rows=[_row(5)]), created_at=NOW)
        raw = bytearray(open(path, "rb").read())
        raw[HEADER_SIZE + 40] ^= 0x01
        open(path, "wb").write(bytes(raw))
        with pytest.raises(SnapshotError, match="payload CRC"):
            load_snapshot(path)

    def test_torn_payload_rejected(self, tmp_path):
        path = str(tmp_path / "slab.snap")
        write_snapshot(path, _table(), created_at=NOW)
        raw = open(path, "rb").read()
        open(path, "wb").write(raw[: len(raw) // 2])
        with pytest.raises(SnapshotError, match="torn"):
            load_snapshot(path)

    def test_truncated_header_rejected(self, tmp_path):
        path = str(tmp_path / "slab.snap")
        open(path, "wb").write(MAGIC)
        with pytest.raises(SnapshotError, match="truncated header"):
            load_snapshot(path)

    def test_missing_file_raises_snapshot_error(self, tmp_path):
        with pytest.raises(SnapshotError):
            load_snapshot(str(tmp_path / "nope.snap"))

    def test_column_constants_mirror_ops_slab(self):
        """persist redeclares the row format so offline tools skip the jax
        import; the mirror must never drift from the device layout."""
        from api_ratelimit_tpu.ops import slab as ops_slab
        from api_ratelimit_tpu.persist import snapshot as persist_snap

        assert persist_snap.ROW_WIDTH == ops_slab.ROW_WIDTH
        for col in ("COL_FP_LO", "COL_FP_HI", "COL_COUNT", "COL_WINDOW",
                    "COL_EXPIRE", "COL_DIVIDER"):
            assert getattr(persist_snap, col) == getattr(ops_slab, col), col


class TestReconcile:
    def test_live_row_inside_window_kept(self):
        table = _table(rows=[_row(3, count=7)])
        out, stats = reconcile_rows(table, NOW)
        assert stats == {"restored": 1, "dropped_expired": 0,
                         "dropped_window": 0}
        np.testing.assert_array_equal(out, table)

    def test_expired_row_dropped(self):
        table = _table(rows=[_row(3, expire=NOW - 1)])
        out, stats = reconcile_rows(table, NOW)
        assert stats["dropped_expired"] == 1 and stats["restored"] == 0
        assert not out.any()

    def test_window_ended_but_ttl_pinned_dropped(self):
        # jittered TTL still open, fixed window closed: the row carries no
        # decision state (next touch rolls to base 0), so restore drops it
        # — the same population the in-kernel eviction scan reclaims
        # ahead of any live-window row
        table = _table(rows=[_row(3, window=NOW - 120, expire=NOW + 200)])
        out, stats = reconcile_rows(table, NOW)
        assert stats["dropped_window"] == 1 and stats["restored"] == 0
        assert not out.any()

    def test_legacy_divider_zero_keeps_ttl_rule(self):
        table = _table(rows=[_row(3, window=NOW - 120, divider=0)])
        _out, stats = reconcile_rows(table, NOW)
        assert stats["restored"] == 1  # TTL-only rule for pre-divider rows

    def test_empty_rows_not_counted(self):
        out, stats = reconcile_rows(_table(), NOW)
        assert stats == {"restored": 0, "dropped_expired": 0,
                         "dropped_window": 0}
        assert not out.any()


def _engine(ts, n_slots=1 << 10):
    return SlabDeviceEngine(
        ts, n_slots=n_slots, use_pallas=False, buckets=(128,)
    )


def _hit(engine, fp=0xBEEF, n=1, limit=10, divider=1000):
    return engine.submit(
        [_Item(fp=fp, hits=1, limit=limit, divider=divider, jitter=0)] * n
    )


class TestSnapshotter:
    def test_snapshot_restore_round_trip(self, tmp_path):
        ts = FakeTimeSource(NOW)
        eng = _engine(ts)
        _hit(eng, n=4)
        snap = SlabSnapshotter(eng, str(tmp_path), interval_ms=1000,
                               time_source=ts)
        assert snap.snapshot_once() > 0
        assert snap.writes_total == 1
        assert os.path.exists(tmp_path / "slab.snap")

        eng2 = _engine(ts)
        snap2 = SlabSnapshotter(eng2, str(tmp_path), interval_ms=1000,
                                time_source=ts)
        stats = snap2.restore()
        assert stats["restored"] == 1  # one live slot row
        assert _hit(eng2) == [5]  # counter continues where eng left it

    def test_no_snapshot_boots_cold(self, tmp_path):
        ts = FakeTimeSource(NOW)
        eng = _engine(ts)
        snap = SlabSnapshotter(eng, str(tmp_path), interval_ms=1000,
                               time_source=ts)
        assert snap.restore() == {"restored": False, "reason": "no snapshot"}
        assert snap.load_rejected_total == 0  # absence is not corruption

    def test_topology_mismatch_boots_cold(self, tmp_path):
        ts = FakeTimeSource(NOW)
        eng = _engine(ts, n_slots=1 << 10)
        _hit(eng, n=3)
        SlabSnapshotter(eng, str(tmp_path), interval_ms=1000,
                        time_source=ts).snapshot_once()

        small = _engine(ts, n_slots=1 << 9)
        snap = SlabSnapshotter(small, str(tmp_path), interval_ms=1000,
                               time_source=ts)
        stats = snap.restore()
        assert stats["restored"] is False
        assert snap.load_rejected_total == 1
        assert _hit(small) == [1]  # cold

    def test_corrupt_snapshot_boots_cold(self, tmp_path):
        ts = FakeTimeSource(NOW)
        eng = _engine(ts)
        _hit(eng, n=3)
        SlabSnapshotter(eng, str(tmp_path), interval_ms=1000,
                        time_source=ts).snapshot_once()
        path = tmp_path / "slab.snap"
        raw = bytearray(path.read_bytes())
        raw[HEADER_SIZE + 8] ^= 0xFF
        path.write_bytes(bytes(raw))

        eng2 = _engine(ts)
        snap2 = SlabSnapshotter(eng2, str(tmp_path), interval_ms=1000,
                                time_source=ts)
        assert snap2.restore()["restored"] is False
        assert snap2.load_rejected_total == 1
        assert _hit(eng2) == [1]

    def test_restore_reconciles_against_clock(self, tmp_path):
        ts = FakeTimeSource(NOW)
        eng = _engine(ts)
        _hit(eng, n=4, divider=1000)
        SlabSnapshotter(eng, str(tmp_path), interval_ms=1000,
                        time_source=ts).snapshot_once()
        # restart far in the future: the window (and TTL) are long gone
        ts2 = FakeTimeSource(NOW + 5000)
        eng2 = _engine(ts2)
        snap2 = SlabSnapshotter(eng2, str(tmp_path), interval_ms=1000,
                                time_source=ts2)
        stats = snap2.restore()
        # loaded fine ('reason' absent) but the row was reconciled away
        assert "reason" not in stats
        assert stats["restored"] == 0 and stats["dropped_expired"] == 1
        assert _hit(eng2) == [1]  # fresh window, fresh count

    def test_drain_takes_final_snapshot(self, tmp_path):
        ts = FakeTimeSource(NOW)
        eng = _engine(ts)
        _hit(eng, n=2)
        snap = SlabSnapshotter(eng, str(tmp_path), interval_ms=60_000,
                               time_source=ts)
        assert snap.drain() > 0
        assert snap.writes_total == 1
        # the engine is quiesced: submits now fail (batcher drained)
        from api_ratelimit_tpu.limiter.cache import CacheError

        with pytest.raises(CacheError):
            _hit(eng)
        # and the next process warm-boots the drained state exactly
        eng2 = _engine(ts)
        SlabSnapshotter(eng2, str(tmp_path), interval_ms=1000,
                        time_source=ts).restore()
        assert _hit(eng2) == [3]

    def test_periodic_thread_writes(self, tmp_path):
        import time as _time

        ts = FakeTimeSource(NOW)
        eng = _engine(ts)
        _hit(eng)
        snap = SlabSnapshotter(eng, str(tmp_path), interval_ms=20,
                               time_source=ts)
        snap.start()
        try:
            deadline = _time.monotonic() + 5.0
            while snap.writes_total < 2 and _time.monotonic() < deadline:
                _time.sleep(0.01)
        finally:
            snap.stop()
        assert snap.writes_total >= 2
        assert os.path.exists(tmp_path / "slab.snap")

    def test_stats_and_age(self, tmp_path):
        ts = FakeTimeSource(NOW)
        store = Store(TestSink())
        eng = _engine(ts)
        _hit(eng, n=2)
        snap = SlabSnapshotter(eng, str(tmp_path), interval_ms=1000,
                               stale_after_ms=5000, time_source=ts,
                               scope=store.scope("ratelimit"))
        assert snap.age_seconds() == -1.0  # never started, never succeeded
        assert snap.stale_reason() is None
        snap.snapshot_once()
        gauges = store.metrics_snapshot()["gauges"]
        counters = store.metrics_snapshot()["counters"]
        assert counters["ratelimit.snapshot.writes"] == 1
        assert gauges["ratelimit.snapshot.bytes"] > 0
        ts.advance(3)
        store.flush()  # runs the age generator
        assert store.metrics_snapshot()["gauges"][
            "ratelimit.snapshot.age_seconds"
        ] == 3
        assert snap.stale_reason() is None
        ts.advance(10)  # past the 5s staleness budget
        reason = snap.stale_reason()
        assert reason is not None and "stale" in reason

        eng2 = _engine(ts)
        store2 = Store(TestSink())
        snap2 = SlabSnapshotter(eng2, str(tmp_path), interval_ms=1000,
                                time_source=ts,
                                scope=store2.scope("ratelimit"))
        snap2.restore()
        g2 = store2.metrics_snapshot()["gauges"]
        assert g2["ratelimit.snapshot.restore_rows"] == 1
        assert g2["ratelimit.snapshot.restore_dropped_expired"] == 0

    def test_snapshot_under_concurrent_traffic(self, tmp_path):
        """The quiesce-and-copy contract under fire: submits hammer the
        engine from several threads while a snapshot loop runs flat out.
        No crash, no lost increments (the copy never aliases a donated
        buffer), and the surviving file is itself valid and loadable."""
        import threading

        ts = FakeTimeSource(NOW)
        eng = _engine(ts)
        snap = SlabSnapshotter(eng, str(tmp_path), interval_ms=60_000,
                               time_source=ts)
        n_threads, per = 4, 50

        def worker():
            for _ in range(per):
                _hit(eng)

        stop = threading.Event()

        def snapper():
            while not stop.is_set():
                snap.snapshot_once()

        snapper_t = threading.Thread(target=snapper)
        workers = [threading.Thread(target=worker) for _ in range(n_threads)]
        snapper_t.start()
        for t in workers:
            t.start()
        for t in workers:
            t.join()
        stop.set()
        snapper_t.join()
        assert snap.writes_total > 0 and snap.write_errors_total == 0
        assert _hit(eng) == [n_threads * per + 1]  # every increment counted
        _header, table = load_snapshot(str(tmp_path / "slab.snap"))
        assert table.any()

    def test_shard_file_names(self, tmp_path):
        assert snapshot_paths("d", 1) == [os.path.join("d", "slab.snap")]
        assert snapshot_paths("d", 2) == [
            os.path.join("d", "slab.00-of-02.snap"),
            os.path.join("d", "slab.01-of-02.snap"),
        ]


class TestShardedSnapshot:
    @pytest.fixture()
    def mesh(self):
        import jax

        from api_ratelimit_tpu.parallel import sharded_slab

        if sharded_slab.shard_map is None:
            pytest.skip("no shard_map in this jax")
        assert len(jax.devices()) == 8
        from api_ratelimit_tpu.parallel import make_mesh

        return make_mesh()

    @staticmethod
    def _packed(b, now=NOW):
        packed = np.zeros((7, b), dtype=np.uint32)
        ids = np.arange(b, dtype=np.uint64)
        packed[0] = (ids * 0x9E3779B185EBCA87 & 0xFFFFFFFF).astype(np.uint32)
        packed[1] = ((ids ^ 0x77) * 0xC2B2AE3D27D4EB4F & 0xFFFFFFFF).astype(
            np.uint32
        )
        packed[2] = 1
        packed[3] = 100
        packed[4] = 1000
        packed[6, 0] = np.uint32(now)
        packed[6, 1] = np.float32(0.8).view(np.uint32)
        return packed

    def test_per_shard_files_and_warm_continuation(self, tmp_path, mesh):
        from api_ratelimit_tpu.parallel import ShardedSlabEngine

        ts = FakeTimeSource(NOW)
        # ways pinned: the exact-continuation assert needs every key to
        # survive the single fresh 128-key batch, and this fixture's
        # synthetic fingerprints are spread for the 128-lane geometry
        # (at the CPU auto default of 8 they alias pairwise on the way
        # rotation and half the batch drops as counted way contention)
        eng = ShardedSlabEngine(mesh=mesh, n_slots_global=8 * 256, ways=128)
        packed = self._packed(128)
        first = np.asarray(eng.step_after_compact(packed.copy(), cap=0xFFFF))
        snap = SlabSnapshotter(eng, str(tmp_path), interval_ms=1000,
                               time_source=ts)
        snap.snapshot_once()
        files = sorted(os.listdir(tmp_path))
        assert files == [f"slab.{i:02d}-of-08.snap" for i in range(8)]

        eng2 = ShardedSlabEngine(mesh=mesh, n_slots_global=8 * 256, ways=128)
        snap2 = SlabSnapshotter(eng2, str(tmp_path), interval_ms=1000,
                                time_source=ts)
        assert snap2.restore()["restored"] == 128
        second = np.asarray(eng2.step_after_compact(packed.copy(), cap=0xFFFF))
        np.testing.assert_array_equal(second, first + 1)

    def test_one_bad_shard_rejects_whole_set(self, tmp_path, mesh):
        from api_ratelimit_tpu.parallel import ShardedSlabEngine

        ts = FakeTimeSource(NOW)
        eng = ShardedSlabEngine(mesh=mesh, n_slots_global=8 * 256)
        eng.step_after_compact(self._packed(64), cap=0xFFFF)
        SlabSnapshotter(eng, str(tmp_path), interval_ms=1000,
                        time_source=ts).snapshot_once()
        bad = tmp_path / "slab.03-of-08.snap"
        raw = bytearray(bad.read_bytes())
        raw[HEADER_SIZE + 4] ^= 0x55
        bad.write_bytes(bytes(raw))

        eng2 = ShardedSlabEngine(mesh=mesh, n_slots_global=8 * 256)
        snap2 = SlabSnapshotter(eng2, str(tmp_path), interval_ms=1000,
                                time_source=ts)
        assert snap2.restore()["restored"] is False
        assert snap2.load_rejected_total == 1
        assert eng2.health_snapshot(now=NOW)["live_slots"] == 0  # cold


class TestSnapshotFaultInjection:
    """The snapshot.write / snapshot.load chaos sites: a fault-injected bad
    snapshot must be REJECTED at load and fall back to a cold slab, counted
    in snapshot.load_rejected — never a crash, never a corrupt restore."""

    def test_write_error_counted_not_fatal(self, tmp_path):
        ts = FakeTimeSource(NOW)
        eng = _engine(ts)
        _hit(eng)
        faults = FaultInjector.from_spec("snapshot.write:error:1.0")
        snap = SlabSnapshotter(eng, str(tmp_path), interval_ms=1000,
                               time_source=ts, fault_injector=faults)
        assert snap.snapshot_once() == 0
        assert snap.write_errors_total == 1
        assert not os.path.exists(tmp_path / "slab.snap")
        faults.clear()
        assert snap.snapshot_once() > 0  # outage over, writes recover

    def test_torn_write_rejected_at_load(self, tmp_path):
        ts = FakeTimeSource(NOW)
        eng = _engine(ts)
        _hit(eng, n=2)
        faults = FaultInjector.from_spec("snapshot.write:torn_write:1.0")
        SlabSnapshotter(eng, str(tmp_path), interval_ms=1000,
                        time_source=ts,
                        fault_injector=faults).snapshot_once()
        assert faults.fired().get("snapshot.write:torn_write") == 1

        eng2 = _engine(ts)
        snap2 = SlabSnapshotter(eng2, str(tmp_path), interval_ms=1000,
                                time_source=ts)
        assert snap2.restore()["restored"] is False
        assert snap2.load_rejected_total == 1
        assert _hit(eng2) == [1]  # cold boot, service keeps working

    def test_corrupt_write_rejected_at_load(self, tmp_path):
        ts = FakeTimeSource(NOW)
        eng = _engine(ts)
        _hit(eng, n=2)
        faults = FaultInjector.from_spec("snapshot.write:corrupt:1.0")
        SlabSnapshotter(eng, str(tmp_path), interval_ms=1000,
                        time_source=ts,
                        fault_injector=faults).snapshot_once()

        eng2 = _engine(ts)
        snap2 = SlabSnapshotter(eng2, str(tmp_path), interval_ms=1000,
                                time_source=ts)
        assert snap2.restore()["restored"] is False
        assert snap2.load_rejected_total == 1

    def test_load_faults_reject_good_file(self, tmp_path):
        ts = FakeTimeSource(NOW)
        eng = _engine(ts)
        _hit(eng, n=2)
        SlabSnapshotter(eng, str(tmp_path), interval_ms=1000,
                        time_source=ts).snapshot_once()
        for spec in ("snapshot.load:error:1.0", "snapshot.load:corrupt:1.0"):
            eng2 = _engine(ts)
            snap2 = SlabSnapshotter(
                eng2, str(tmp_path), interval_ms=1000, time_source=ts,
                fault_injector=FaultInjector.from_spec(spec),
            )
            assert snap2.restore()["restored"] is False, spec
            assert snap2.load_rejected_total == 1, spec
            assert _hit(eng2) == [1], spec

    def test_new_fault_kinds_parse_and_junk_rejected(self):
        from api_ratelimit_tpu.testing.faults import parse_fault_spec

        rules = parse_fault_spec(
            "snapshot.write:torn_write:0.5,snapshot.load:corrupt:1.0"
        )
        assert [(r.site, r.kind) for r in rules] == [
            ("snapshot.write", "torn_write"),
            ("snapshot.load", "corrupt"),
        ]
        with pytest.raises(ValueError):
            parse_fault_spec("snapshot.write:torn_write:1.5")  # prob > 1
        with pytest.raises(ValueError):
            parse_fault_spec("snapshot.write:shred:1.0")  # unknown kind


def _load_inspect():
    spec = importlib.util.spec_from_file_location(
        "snapshot_inspect", os.path.join(REPO, "tools", "snapshot_inspect.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestSnapshotInspectCli:
    def test_reports_valid_file(self, tmp_path, capsys):
        path = str(tmp_path / "slab.snap")
        write_snapshot(path, _table(rows=[_row(3, count=7), _row(9)]),
                       created_at=NOW)
        tool = _load_inspect()
        assert tool.main([path]) == 0
        out = capsys.readouterr().out
        assert "CRC OK" in out and "occupied=2" in out

    def test_json_mode(self, tmp_path, capsys):
        path = str(tmp_path / "slab.snap")
        write_snapshot(path, _table(rows=[_row(3, count=7)]), created_at=NOW,
                       shard_index=1, shard_count=2)
        tool = _load_inspect()
        assert tool.main(["--json", "--now", str(NOW), path]) == 0
        reports = json.loads(capsys.readouterr().out)
        assert reports[0]["valid"] is True
        assert reports[0]["shard"] == "1/2"
        assert reports[0]["rows"]["occupied"] == 1
        assert reports[0]["rows"]["restorable"] == 1
        assert reports[0]["rows"]["count_sum"] == 7

    def test_invalid_file_exits_nonzero(self, tmp_path, capsys):
        good = str(tmp_path / "good.snap")
        bad = str(tmp_path / "bad.snap")
        write_snapshot(good, _table(), created_at=NOW)
        write_snapshot(bad, _table(), created_at=NOW)
        raw = bytearray(open(bad, "rb").read())
        raw[HEADER_SIZE] ^= 0xFF
        open(bad, "wb").write(bytes(raw))
        tool = _load_inspect()
        assert tool.main(["--json", good, bad]) == 1
        reports = json.loads(capsys.readouterr().out)
        assert [r["valid"] for r in reports] == [True, False]
        assert "CRC" in reports[1]["error"]

    def test_cli_never_imports_jax(self):
        """Deploy tooling inspects snapshots on jax-less boxes; importing
        the CLI (and the persist package under it) must not pull jax in."""
        import subprocess

        code = (
            "import sys; sys.path.insert(0, %r); "
            "import tools.snapshot_inspect; "
            "assert 'jax' not in sys.modules, 'CLI imported jax'; "
            "print('ok')" % REPO
        )
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=120, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr
        assert proc.stdout.strip() == "ok"


class TestSetMigration:
    """The boot migration into the set-associative geometry: v1
    (open-addressed, PR 4-era) snapshots — and v2 snapshots written under
    a different SLAB_WAYS — are REHASHED into the running layout at
    restore, never rejected, with live counters preserved exactly."""

    def test_migrate_places_rows_by_set_index(self):
        from api_ratelimit_tpu.persist.snapshot import migrate_rows_to_sets

        # 64 rows / 8 ways = 8 sets; a row whose fp_lo selects set 3 sits
        # at (open-addressed) slot 0 and must land inside rows [24, 32)
        t = _table(rows=[(0, 0x13, 5, NOW - 30, NOW + 90, 60)])
        out, stats = migrate_rows_to_sets(t, ways=8)
        assert stats == {"placed": 1, "dropped_overflow": 0}
        placed = np.flatnonzero(out.any(axis=1))
        assert placed.tolist() == [(0x13 & 7) * 8]  # set 3, way 0
        np.testing.assert_array_equal(out[placed[0]], t[0])

    def test_overflowing_set_drops_lowest_counts(self):
        from api_ratelimit_tpu.persist.snapshot import migrate_rows_to_sets

        # 8 rows / 2 ways = 4 sets; six rows all hash to set 1 — the two
        # LOWEST counts are the overflow casualties (the same
        # least-valuable-first rule the in-kernel eviction applies)
        rows = [
            (slot, 0x10 * slot + 1, count, NOW - 30, NOW + 90, 60)
            for slot, count in zip(range(6), (4, 9, 1, 7, 2, 6))
        ]
        t = _table(n=8, rows=rows)
        out, stats = migrate_rows_to_sets(t, ways=2)
        assert stats == {"placed": 2, "dropped_overflow": 4}
        kept = sorted(out[out.any(axis=1)][:, 2].tolist())
        assert kept == [7, 9]

    def test_set_occupancy_histogram(self):
        from api_ratelimit_tpu.persist.snapshot import set_occupancy_histogram

        t = _table(
            n=16,
            rows=[
                (0, 1, 3, NOW - 30, NOW + 90, 60),
                (1, 2, 3, NOW - 30, NOW + 90, 60),
                (4, 3, 3, NOW - 30, NOW - 10, 60),  # expired
            ],
        )
        hist = set_occupancy_histogram(t, ways=4)  # 4 sets
        assert hist.tolist() == [2, 1, 1, 0, 0]  # by occupied rows
        hist_live = set_occupancy_histogram(t, ways=4, now=NOW)
        assert hist_live.tolist() == [3, 0, 1, 0, 0]

    def test_v1_snapshot_round_trips_through_boot_migration(self, tmp_path):
        """THE regression pin for the acceptance criterion: a PR 4-era v1
        fixture (row at its open-addressed probe slot, version 1, no ways
        stamp) restores through the migration with zero dropped live
        counters, and the counter continues where it left off."""
        ts = FakeTimeSource(NOW)
        eng = _engine(ts)  # 1024 slots, auto ways (4 on the CPU suite)
        window = NOW - (NOW % 1000)
        table = np.zeros((1024, 8), dtype=np.uint32)
        # fp 0xBEEF's OLD home: probe candidate 0 = fp_lo mod n_slots —
        # NOT its set-associative home (set fp_lo mod n_sets)
        table[0xBEEF % 1024] = [0xBEEF, 0, 4, window, NOW + 1000, 1000, 0, 0]
        write_snapshot(
            str(tmp_path / "slab.snap"), table, created_at=NOW, version=1
        )
        header = read_header(str(tmp_path / "slab.snap"))
        assert header.version == 1 and header.ways == 0

        snap = SlabSnapshotter(
            eng, str(tmp_path), interval_ms=1000, time_source=ts
        )
        stats = snap.restore()
        assert "reason" not in stats  # loaded, not rejected
        assert stats["restored"] == 1
        assert stats["migrated"] == 1
        assert stats["dropped_overflow"] == 0  # zero dropped live counters
        assert _hit(eng) == [5]  # 4 restored + 1: the counter continued

    def test_v2_written_under_different_ways_rehashes(self, tmp_path):
        ts = FakeTimeSource(NOW)
        eng = SlabDeviceEngine(
            ts, n_slots=1 << 10, ways=32, use_pallas=False, buckets=(128,)
        )
        _hit(eng, n=3)
        SlabSnapshotter(
            eng, str(tmp_path), interval_ms=1000, time_source=ts
        ).snapshot_once()
        assert read_header(str(tmp_path / "slab.snap")).ways == 32

        eng2 = _engine(ts)  # default ways=128: geometry changed
        stats = SlabSnapshotter(
            eng2, str(tmp_path), interval_ms=1000, time_source=ts
        ).restore()
        assert stats["restored"] == 1 and stats["migrated"] == 1
        assert _hit(eng2) == [4]

    def test_same_geometry_restore_skips_rehash(self, tmp_path):
        ts = FakeTimeSource(NOW)
        eng = _engine(ts)
        _hit(eng, n=2)
        SlabSnapshotter(
            eng, str(tmp_path), interval_ms=1000, time_source=ts
        ).snapshot_once()
        header = read_header(str(tmp_path / "slab.snap"))
        assert header.version == SNAPSHOT_VERSION and header.ways == eng.ways

        eng2 = _engine(ts)
        stats = SlabSnapshotter(
            eng2, str(tmp_path), interval_ms=1000, time_source=ts
        ).restore()
        assert stats["restored"] == 1 and stats["migrated"] == 0
        assert _hit(eng2) == [3]

    def test_restore_counts_set_overflow(self, tmp_path):
        """A v1 fixture denser than one set can hold: the lowest-count
        rows drop (counted as dropped_overflow), the highest survive."""
        ts = FakeTimeSource(NOW)
        eng = SlabDeviceEngine(
            ts, n_slots=8, ways=4, use_pallas=False, buckets=(8,)
        )
        window = NOW - (NOW % 1000)
        table = np.zeros((8, 8), dtype=np.uint32)
        # six live rows, all fp_lo even => all in set 0 of 2 (8 slots / 4)
        for slot, (fp_lo, count) in enumerate(
            [(2, 1), (4, 2), (6, 3), (8, 4), (10, 5), (12, 6)]
        ):
            table[slot] = [fp_lo, 0, count, window, NOW + 1000, 1000, 0, 0]
        write_snapshot(
            str(tmp_path / "slab.snap"), table, created_at=NOW, version=1
        )
        stats = SlabSnapshotter(
            eng, str(tmp_path), interval_ms=1000, time_source=ts
        ).restore()
        assert stats["restored"] == 6  # live rows in the file
        assert stats["migrated"] == 4  # what fit into the 4-way set
        assert stats["dropped_overflow"] == 2
        # survivors continue exactly; casualties fail open and restart
        assert _hit(eng, fp=12, divider=1000) == [7]
        assert _hit(eng, fp=2, divider=1000) == [1]


class TestSnapshotInspectSetView:
    def test_set_occupancy_section_renders(self, tmp_path, capsys):
        ts = FakeTimeSource(NOW)
        # explicit geometry so the rendered numbers are deterministic on
        # any platform (the engine default auto-selects by device)
        eng = SlabDeviceEngine(
            ts, n_slots=1 << 10, ways=128, use_pallas=False, buckets=(128,)
        )
        _hit(eng, n=2)
        SlabSnapshotter(
            eng, str(tmp_path), interval_ms=1000, time_source=ts
        ).snapshot_once()
        tool = _load_inspect()
        path = str(tmp_path / "slab.snap")
        assert tool.main(["--json", "--now", str(NOW), path]) == 0
        report = json.loads(capsys.readouterr().out)[0]
        assert report["version"] == SNAPSHOT_VERSION
        assert report["needs_migration"] is False
        sets = report["sets"]
        assert sets["ways"] == 128 and sets["n_sets"] == 8
        # one occupied row: 7 empty sets, 1 set holding 1 row
        assert sets["occupancy_histogram"] == {"0": 7, "1": 1}
        assert sets["full_sets"] == 0 and sets["max_set_occupancy"] == 1
        # the human rendering mentions the set geometry
        assert tool.main(["--now", str(NOW), path]) == 0
        out = capsys.readouterr().out
        assert "8 x 128-way" in out

    def test_v1_file_reports_migration_needed(self, tmp_path, capsys):
        table = np.zeros((64, 8), dtype=np.uint32)
        table[5] = [0x15, 0, 2, NOW - 30, NOW + 90, 60, 0, 0]
        path = str(tmp_path / "old.snap")
        write_snapshot(path, table, created_at=NOW, version=1)
        tool = _load_inspect()
        assert tool.main(["--json", "--now", str(NOW), path]) == 0
        report = json.loads(capsys.readouterr().out)[0]
        assert report["valid"] is True  # old versions load, never reject
        assert report["version"] == 1
        assert report["needs_migration"] is True
        assert report["sets"] is None  # placement is pre-migration
        assert report["rows"]["restorable"] == 1
