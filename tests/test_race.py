"""Concurrency races + property-based differential fuzzing.

The reference runs its whole suite under `go test -race` (Makefile:83-89)
and unit-tests its known race windows (memcache add/increment, locked rand,
burst sampler CAS — SURVEY.md §5.2). Python has no race detector, so these
tests attack the same windows directly: many threads hammering the hot path
while config reloads swap state underneath, plus hypothesis-driven random
op streams holding the slab engine to the memory oracle.
"""

from __future__ import annotations

import os
import threading

import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

# Default example counts keep the suite fast; an extended campaign sets
# SLAB_FUZZ_EXAMPLES (e.g. 2000) to mine the same differential properties
# much deeper on idle hardware.
FUZZ_EXAMPLES = int(os.environ.get("SLAB_FUZZ_EXAMPLES", "0") or 0)

from api_ratelimit_tpu.backends.memory import MemoryRateLimitCache
from api_ratelimit_tpu.limiter.base_limiter import BaseRateLimiter
from api_ratelimit_tpu.models.config import RateLimit, new_rate_limit_stats
from api_ratelimit_tpu.models.descriptors import Descriptor, RateLimitRequest
from api_ratelimit_tpu.models.response import RateLimitValue
from api_ratelimit_tpu.models.units import Unit
from api_ratelimit_tpu.service.ratelimit import RateLimitService
from api_ratelimit_tpu.utils.timeutil import FakeTimeSource


class _MutableRuntime:
    """Runtime whose snapshot can be swapped between reloads."""

    def __init__(self, yaml_text: str):
        self.yaml_text = yaml_text
        self._lock = threading.Lock()

    def snapshot(self):
        outer = self

        class Snap:
            def keys(self):
                return ["config.test"]

            def get(self, key):
                with outer._lock:
                    return outer.yaml_text

        return Snap()

    def add_update_callback(self, cb):
        pass

    def set_yaml(self, text: str):
        with self._lock:
            self.yaml_text = text


_YAML_A = """\
domain: racing
descriptors:
  - key: k
    rate_limit: {unit: hour, requests_per_unit: 1000000}
"""

_YAML_B = """\
domain: racing
descriptors:
  - key: k
    rate_limit: {unit: hour, requests_per_unit: 999999}
  - key: other
    rate_limit: {unit: minute, requests_per_unit: 5}
"""


class TestReloadUnderFire:
    def test_hot_path_races_config_reload(self, test_store):
        """Requests must never observe a broken config mid-swap: every call
        either resolves against config A or config B, and reloads never
        raise (ratelimit.go's RWMutex window, :302-306)."""
        store, _ = test_store
        ts = FakeTimeSource(1000)
        base = BaseRateLimiter(time_source=ts, jitter_rand=None)
        runtime = _MutableRuntime(_YAML_A)
        service = RateLimitService(
            runtime=runtime,
            cache=MemoryRateLimitCache(base),
            stats_scope=store.scope("ratelimit").scope("service"),
            time_source=ts,
        )
        errors: list[BaseException] = []
        stop = threading.Event()

        def hammer():
            req = RateLimitRequest(
                domain="racing", descriptors=(Descriptor.of(("k", "v")),)
            )
            while not stop.is_set():
                try:
                    overall, statuses, _ = service.should_rate_limit(req)
                    # limit must come from exactly config A or config B
                    rpu = statuses[0].current_limit.requests_per_unit
                    if rpu not in (1_000_000, 999_999):
                        raise AssertionError(f"torn config: {rpu}")
                except BaseException as e:  # noqa: BLE001
                    errors.append(e)
                    return

        def reloader():
            flip = False
            while not stop.is_set():
                runtime.set_yaml(_YAML_B if flip else _YAML_A)
                try:
                    service.reload_config()
                except BaseException as e:  # noqa: BLE001
                    errors.append(e)
                    return
                flip = not flip

        threads = [threading.Thread(target=hammer) for _ in range(8)]
        threads.append(threading.Thread(target=reloader))
        for t in threads:
            t.start()
        import time

        time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join(5.0)
        assert not errors

    def test_memory_backend_concurrent_counts_exact(self, test_store):
        """N threads x M hits on one key must count to exactly N*M — the
        memory backend's lock must serialize increments."""
        store, _ = test_store
        ts = FakeTimeSource(5000)
        base = BaseRateLimiter(time_source=ts, jitter_rand=None)
        cache = MemoryRateLimitCache(base)
        scope = store.scope("t")
        limit = RateLimit(
            full_key="k",
            stats=new_rate_limit_stats(scope, "k"),
            limit=RateLimitValue(requests_per_unit=1_000_000, unit=Unit.HOUR),
        )
        req = RateLimitRequest(
            domain="c", descriptors=(Descriptor.of(("k", "v")),)
        )
        n_threads, per_thread = 8, 200
        results: list[int] = []
        lock = threading.Lock()

        def worker():
            local = []
            for _ in range(per_thread):
                resp = cache.do_limit(req, [limit])
                local.append(resp.descriptor_statuses[0].limit_remaining)
            with lock:
                results.extend(local)

        threads = [threading.Thread(target=worker) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10.0)
        total = n_threads * per_thread
        # every decision got a distinct remaining value => exact serialization
        assert len(set(results)) == total
        assert min(results) == 1_000_000 - total

    def test_pipelined_batcher_concurrent_counts_exact(self, test_store):
        """Same exactness through the DOUBLE-BUFFERED tpu backend: the
        dispatcher launches batch k+1 while the collector drains batch k's
        readback (backends/batcher.py), and no result may be lost,
        duplicated, or misrouted across that handoff."""
        from api_ratelimit_tpu.backends.tpu import TpuRateLimitCache

        store, _ = test_store
        base = BaseRateLimiter(time_source=FakeTimeSource(5000), jitter_rand=None)
        cache = TpuRateLimitCache(
            base, n_slots=1 << 12, batch_window_seconds=0.0005, max_batch=256
        )
        scope = store.scope("t")
        limit = RateLimit(
            full_key="k",
            stats=new_rate_limit_stats(scope, "k"),
            limit=RateLimitValue(requests_per_unit=1_000_000, unit=Unit.HOUR),
        )
        req = RateLimitRequest(
            domain="c", descriptors=(Descriptor.of(("k", "v")),)
        )
        n_threads, per_thread = 8, 100
        results: list[int] = []
        lock = threading.Lock()

        def worker():
            local = []
            for _ in range(per_thread):
                resp = cache.do_limit(req, [limit])
                local.append(resp.descriptor_statuses[0].limit_remaining)
            with lock:
                results.extend(local)

        threads = [threading.Thread(target=worker) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30.0)
        cache.close()
        total = n_threads * per_thread
        assert len(results) == total
        assert len(set(results)) == total
        assert min(results) == 1_000_000 - total


class TestSlabPropertyDifferential:
    """hypothesis-driven random op streams: the slab engine must agree with
    the memory oracle on every decision code (the §4.4 differential oracle,
    fuzzed rather than hand-cased)."""

    @settings(max_examples=FUZZ_EXAMPLES or 20, deadline=None)
    @given(
        ops=st.lists(
            st.tuples(
                st.integers(min_value=0, max_value=7),  # key id
                # mostly small hits; occasionally large enough to push
                # counters across the u8/u16 readback-width boundaries
                st.one_of(
                    st.integers(min_value=1, max_value=3),
                    st.sampled_from([100, 40000]),
                ),
                st.integers(min_value=0, max_value=90),  # seconds to advance
            ),
            min_size=1,
            max_size=60,
        ),
        limit_rpu=st.one_of(
            st.integers(min_value=1, max_value=6),
            st.sampled_from([250, 300, 70000]),
        ),
        unit=st.sampled_from([Unit.SECOND, Unit.MINUTE, Unit.HOUR]),
    )
    def test_engine_matches_oracle(self, ops, limit_rpu, unit):
        from api_ratelimit_tpu.backends.tpu import TpuRateLimitCache
        from api_ratelimit_tpu.stats.sinks import NullSink
        from api_ratelimit_tpu.stats.store import Store

        store = Store(NullSink())
        scope = store.scope("t")

        def fresh(name):
            ts = FakeTimeSource(700_000)
            base = BaseRateLimiter(time_source=ts, jitter_rand=None)
            limit = RateLimit(
                full_key=name,
                stats=new_rate_limit_stats(scope, name),
                limit=RateLimitValue(requests_per_unit=limit_rpu, unit=unit),
            )
            return ts, base, limit

        ts_e, base_e, limit_e = fresh("engine")
        ts_o, base_o, limit_o = fresh("oracle")
        engine = TpuRateLimitCache(base_e, n_slots=256)
        oracle = MemoryRateLimitCache(base_o)

        try:
            for key_id, hits, advance in ops:
                ts_e.advance(advance)
                ts_o.advance(advance)
                req = RateLimitRequest(
                    domain="fuzz",
                    descriptors=(Descriptor.of(("k", f"key{key_id}")),),
                    hits_addend=hits,
                )
                got = engine.do_limit(req, [limit_e]).descriptor_statuses[0]
                want = oracle.do_limit(req, [limit_o]).descriptor_statuses[0]
                assert got.code == want.code, (key_id, hits, advance)
                assert got.limit_remaining == want.limit_remaining
        finally:
            engine.close()


class TestBlockPathPropertyDifferential:
    """The sidecar server's block-native path must be op-for-op identical
    to the per-item engine path under random op streams — duplicates in a
    batch, window rollovers, and counter continuation included."""

    @settings(max_examples=FUZZ_EXAMPLES or 15, deadline=None)
    @given(
        ops=st.lists(
            st.tuples(
                st.integers(min_value=0, max_value=5),  # key id
                st.one_of(  # small hits + width-boundary crossers
                    st.integers(min_value=1, max_value=3),
                    st.sampled_from([100, 40000]),
                ),
                st.integers(min_value=0, max_value=90),  # seconds to advance
                st.integers(min_value=1, max_value=3),  # duplicates in batch
            ),
            min_size=1,
            max_size=30,
        ),
        limit=st.one_of(
            st.integers(min_value=1, max_value=6),
            st.sampled_from([250, 300, 70000]),
        ),
        divider=st.sampled_from([1, 60, 3600]),
    )
    def test_block_matches_item_engine(self, ops, limit, divider):
        import numpy as np

        from api_ratelimit_tpu.backends.tpu import SlabDeviceEngine, _Item

        ts_a, ts_b = FakeTimeSource(700_000), FakeTimeSource(700_000)
        item_eng = SlabDeviceEngine(
            time_source=ts_a, n_slots=256, use_pallas=False
        )
        blk_eng = SlabDeviceEngine(
            time_source=ts_b, n_slots=256, use_pallas=False, block_mode=True
        )
        try:
            for key_id, hits, advance, repeat in ops:
                ts_a.advance(advance)
                ts_b.advance(advance)
                fp = (0x9E3779B97F4A7C15 * (key_id + 1)) & ((1 << 64) - 1)
                items = [
                    _Item(fp=fp, hits=hits, limit=limit, divider=divider, jitter=0)
                ] * repeat
                block = np.zeros((6, repeat), dtype=np.uint32)
                block[0] = fp & 0xFFFFFFFF
                block[1] = fp >> 32
                block[2] = hits
                block[3] = limit
                block[4] = divider
                want = item_eng.submit(items)
                got = blk_eng.submit_block(block)
                assert want == got.tolist(), (key_id, hits, advance, repeat)
        finally:
            item_eng.close()
            blk_eng.close()
