"""Integration tier against REAL redis-server processes.

The reference's integration level boots a real local redis fleet — plain
servers, sentinel-monitored pairs, and cluster-mode sets — and runs the
service against it (/root/reference/Makefile:91-125,
Dockerfile.integration:1-17, test/integration/integration_test.go:49-92).
A fake written by the same author as the client cannot catch protocol
misunderstandings, so this module re-runs the driver/backend scenarios
against actual servers:

  * single node: protocol basics, one-RTT pipelines, implicit pipelining
  * auth (requirepass): fail without, pass with
  * fixed-window cache: the reference's canonical 25-calls-over-a-20-limit
    sequence + differential agreement with the memory oracle
  * sentinel: master resolution through a live redis-sentinel
  * cluster: 3-node cluster assembled over our own driver (ADDSLOTS/MEET),
    slot routing + MOVED handling
  * full runner: BACKEND_TYPE=redis server booted in-process, driven over
    real HTTP /json

Topologies are spawned on ephemeral ports and torn down per test. The whole
module skips (with the reason) when redis-server is not installed — the
hermetic fake-server suite (test_redis_backend.py) still covers every
scenario. CI installs redis-server and runs `make tests_with_redis`.
"""

from __future__ import annotations

import os
import shutil
import socket
import subprocess
import time

import pytest

from api_ratelimit_tpu.backends.redis import RedisRateLimitCache
from api_ratelimit_tpu.backends.redis_driver import (
    RedisClient,
    RedisClusterClient,
    RedisError,
)
from api_ratelimit_tpu.limiter.base_limiter import BaseRateLimiter
from api_ratelimit_tpu.models.config import RateLimit, new_rate_limit_stats
from api_ratelimit_tpu.models.descriptors import Descriptor, RateLimitRequest
from api_ratelimit_tpu.models.response import Code, RateLimitValue
from api_ratelimit_tpu.models.units import Unit
from api_ratelimit_tpu.stats import Store, TestSink
from api_ratelimit_tpu.utils import FakeTimeSource

REDIS_SERVER = shutil.which("redis-server")

pytestmark = pytest.mark.skipif(
    REDIS_SERVER is None,
    reason="redis-server binary not installed (hermetic fake-server suite "
    "covers these scenarios; CI runs this module via `make tests_with_redis`)",
)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class RedisProc:
    """One spawned redis-server (or sentinel), killed on close."""

    def __init__(self, workdir: str, *args: str, sentinel: bool = False):
        self.port = free_port()
        self.addr = f"127.0.0.1:{self.port}"
        if sentinel:
            # sentinel requires its config in a file it can rewrite
            conf = os.path.join(workdir, f"sentinel-{self.port}.conf")
            with open(conf, "w") as f:
                f.write(f"port {self.port}\ndir {workdir}\n" + "\n".join(args) + "\n")
            cmd = [REDIS_SERVER, conf, "--sentinel"]
        else:
            cmd = [
                REDIS_SERVER,
                "--port",
                str(self.port),
                "--dir",
                workdir,
                "--save",
                "",
                "--appendonly",
                "no",
                *args,
            ]
        self.proc = subprocess.Popen(
            cmd, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL
        )
        self._wait_ready(sentinel=sentinel)

    def _wait_ready(self, sentinel: bool, timeout: float = 10.0) -> None:
        deadline = time.monotonic() + timeout
        last: Exception | None = None
        while time.monotonic() < deadline:
            try:
                with socket.create_connection(("127.0.0.1", self.port), 0.5) as s:
                    s.sendall(b"*1\r\n$4\r\nPING\r\n")
                    if s.recv(64).startswith(b"+PONG"):
                        return
            except OSError as e:
                last = e
            time.sleep(0.05)
        self.close()
        raise RuntimeError(f"redis on :{self.port} not ready: {last}")

    def close(self) -> None:
        self.proc.terminate()
        try:
            self.proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            self.proc.kill()


@pytest.fixture
def redis_proc(tmp_path):
    server = RedisProc(str(tmp_path))
    yield server
    server.close()


def make_limit(scope, rpu, unit, key="k_v"):
    return RateLimit(
        full_key=key,
        limit=RateLimitValue(rpu, unit),
        stats=new_rate_limit_stats(scope, key),
    )


def base_limiter(now=5000):
    import random

    return BaseRateLimiter(
        time_source=FakeTimeSource(now=now),
        jitter_rand=random.Random(0),
        expiration_jitter_max_seconds=0,
        local_cache=None,
        near_limit_ratio=0.8,
    )


class TestSingleNode:
    def test_protocol_basics(self, redis_proc):
        client = RedisClient("tcp", redis_proc.addr, pool_size=2)
        try:
            assert client.do_cmd("SET", "a", "1") == "OK"
            assert client.do_cmd("INCRBY", "a", 4) == 5
            assert client.do_cmd("GET", "a") == b"5"
            assert client.do_cmd("TTL", "a") == -1
        finally:
            client.close()

    def test_pipeline_one_rtt(self, redis_proc):
        client = RedisClient("tcp", redis_proc.addr, pool_size=2)
        try:
            replies = client.pipe_do(
                [("INCRBY", "p", 2), ("EXPIRE", "p", 60), ("INCRBY", "p", 3)]
            )
            assert replies == [2, 1, 5]
            assert 0 < client.do_cmd("TTL", "p") <= 60
        finally:
            client.close()

    def test_implicit_pipelining(self, redis_proc):
        client = RedisClient(
            "tcp",
            redis_proc.addr,
            pool_size=2,
            pipeline_window_seconds=0.002,
            pipeline_limit=8,
        )
        try:
            assert client.implicit_pipelining_enabled()
            import threading

            results = [None] * 8
            # concurrent submitters coalesce into shared flushes
            def work(i):
                results[i] = client.pipe_do([("INCRBY", "ip", 1)])[0]

            threads = [threading.Thread(target=work, args=(i,)) for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert sorted(results) == list(range(1, 9))
        finally:
            client.close()

    def test_auth(self, tmp_path):
        server = RedisProc(str(tmp_path), "--requirepass", "hunter2")
        try:
            with pytest.raises(RedisError):
                RedisClient("tcp", server.addr, pool_size=1).do_cmd("PING")
            client = RedisClient("tcp", server.addr, pool_size=1, auth="hunter2")
            assert client.do_cmd("SET", "x", "1") == "OK"
            client.close()
        finally:
            server.close()


class TestFixedCacheAgainstRealRedis:
    def test_over_limit_sequence(self, redis_proc):
        """The reference's canonical integration scenario: 25 calls against a
        20/window rule -> first 20 OK, last 5 OVER_LIMIT
        (test/integration/integration_test.go:334-355)."""
        store = Store(TestSink())
        cache = RedisRateLimitCache(
            RedisClient("tcp", redis_proc.addr, pool_size=2), base_limiter()
        )
        limit = make_limit(store.scope("s"), 20, Unit.HOUR, "seq_v")
        request = RateLimitRequest(
            domain="it", descriptors=(Descriptor.of(("seq", "v")),)
        )
        codes = [
            cache.do_limit(request, [limit]).descriptor_statuses[0].code
            for _ in range(25)
        ]
        assert codes[:20] == [Code.OK] * 20
        assert codes[20:] == [Code.OVER_LIMIT] * 5
        assert limit.stats.over_limit.value() == 5

    def test_ttl_set_on_real_server(self, redis_proc):
        store = Store(TestSink())
        client = RedisClient("tcp", redis_proc.addr, pool_size=2)
        cache = RedisRateLimitCache(client, base_limiter(now=7200))
        limit = make_limit(store.scope("s"), 5, Unit.MINUTE, "ttl_v")
        request = RateLimitRequest(
            domain="it", descriptors=(Descriptor.of(("ttl", "v")),)
        )
        cache.do_limit(request, [limit])
        # window 7200, key it_ttl_v_7200, TTL = unit seconds
        ttl = client.do_cmd("TTL", "it_ttl_v_7200")
        assert 0 < ttl <= 60

    def test_differential_vs_memory_oracle(self, redis_proc):
        import random

        from api_ratelimit_tpu.backends.memory import MemoryRateLimitCache

        rng = random.Random(7)
        store = Store(TestSink())
        ts = FakeTimeSource(now=5000)

        def base():
            limiter = base_limiter()
            limiter.time_source = ts
            return limiter

        redis_cache = RedisRateLimitCache(
            RedisClient("tcp", redis_proc.addr, pool_size=2), base()
        )
        oracle = MemoryRateLimitCache(base())
        limits_a = {
            key: make_limit(store.scope("a"), rpu, unit, key)
            for key, rpu, unit in [
                ("u1", 3, Unit.SECOND),
                ("u2", 5, Unit.MINUTE),
                ("u3", 2, Unit.HOUR),
            ]
        }
        limits_b = {
            k: make_limit(store.scope("b"), v.limit.requests_per_unit, v.limit.unit, k)
            for k, v in limits_a.items()
        }
        for step in range(200):
            if rng.random() < 0.2:
                ts.advance(rng.randrange(0, 3))
            key = rng.choice(list(limits_a))
            req = RateLimitRequest(
                domain="diff",
                descriptors=(Descriptor.of((key, rng.choice(["x", "y"]))),),
            )
            got = redis_cache.do_limit(req, [limits_a[key]]).descriptor_statuses[0]
            want = oracle.do_limit(req, [limits_b[key]]).descriptor_statuses[0]
            assert (got.code, got.limit_remaining) == (
                want.code,
                want.limit_remaining,
            ), f"divergence at step {step} key {key}"


class TestSentinel:
    def test_master_resolution_through_live_sentinel(self, tmp_path, redis_proc):
        master = redis_proc
        sentinel = RedisProc(
            str(tmp_path),
            f"sentinel monitor mymaster 127.0.0.1 {master.port} 1",
            "sentinel down-after-milliseconds mymaster 2000",
            sentinel=True,
        )
        try:
            client = RedisClient(
                "tcp",
                f"mymaster,{sentinel.addr}",
                pool_size=1,
                redis_type="SENTINEL",
            )
            assert client.do_cmd("SET", "via-sentinel", "1") == "OK"
            client.close()
            # the write really landed on the monitored master
            direct = RedisClient("tcp", master.addr, pool_size=1)
            assert direct.do_cmd("GET", "via-sentinel") == b"1"
            direct.close()
        finally:
            sentinel.close()


class TestCluster:
    @pytest.fixture
    def cluster(self, tmp_path):
        """3-node cluster assembled over our own driver: ADDSLOTS in chunks +
        MEET + wait for cluster_state:ok (what redis-cli --cluster create
        does, minus the binary dependency)."""
        nodes = []
        for i in range(3):
            workdir = tmp_path / f"n{i}"
            os.makedirs(workdir)
            nodes.append(
                RedisProc(
                    str(workdir),
                    "--cluster-enabled",
                    "yes",
                    "--cluster-config-file",
                    f"nodes-{i}.conf",
                )
            )
        try:
            clients = [RedisClient("tcp", n.addr, pool_size=1) for n in nodes]
            ranges = [(0, 5460), (5461, 10922), (10923, 16383)]
            for client, (start, end) in zip(clients, ranges):
                slots = list(range(start, end + 1))
                for off in range(0, len(slots), 4096):
                    client.do_cmd("CLUSTER", "ADDSLOTS", *slots[off : off + 4096])
            for client in clients[1:]:
                client.do_cmd("CLUSTER", "MEET", "127.0.0.1", str(nodes[0].port))
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                infos = [c.do_cmd("CLUSTER", "INFO") for c in clients]
                if all(b"cluster_state:ok" in i for i in infos):
                    break
                time.sleep(0.2)
            else:
                raise RuntimeError(f"cluster never converged: {infos!r}")
            for client in clients:
                client.close()
            yield nodes
        finally:
            for n in nodes:
                n.close()

    def test_slot_routing_and_cache(self, cluster):
        client = RedisClusterClient([n.addr for n in cluster], pool_size=1)
        try:
            # keys spread across slots; each lands on its owner and reads back
            for i in range(32):
                assert client.do_cmd("SET", f"ck{i}", str(i)) == "OK"
            for i in range(32):
                assert client.do_cmd("GET", f"ck{i}") == str(i).encode()

            store = Store(TestSink())
            cache = RedisRateLimitCache(client, base_limiter())
            limit = make_limit(store.scope("s"), 2, Unit.HOUR, "cl_v")
            request = RateLimitRequest(
                domain="it", descriptors=(Descriptor.of(("cl", "v")),)
            )
            codes = [
                cache.do_limit(request, [limit]).descriptor_statuses[0].code
                for _ in range(4)
            ]
            assert codes == [Code.OK, Code.OK, Code.OVER_LIMIT, Code.OVER_LIMIT]
        finally:
            client.close()


class TestRunnerAgainstRealRedis:
    def test_json_endpoint_end_to_end(self, tmp_path, redis_proc):
        """Boot the real Runner with BACKEND_TYPE=redis (the reference's
        in-process-runner integration pattern, integration_test.go:251-274)
        and drive it over real HTTP."""
        import json
        import urllib.request

        from api_ratelimit_tpu.runner import Runner
        from api_ratelimit_tpu.settings import Settings

        config_dir = tmp_path / "runtime" / "ratelimit" / "config"
        os.makedirs(config_dir)
        (config_dir / "it.yaml").write_text(
            "domain: it\ndescriptors:\n  - key: r\n    rate_limit:"
            " {unit: hour, requests_per_unit: 2}\n"
        )
        settings = Settings(
            port=free_port(),
            grpc_port=free_port(),
            debug_port=free_port(),
            backend_type="redis",
            redis_socket_type="tcp",
            redis_url=redis_proc.addr,
            runtime_path=str(tmp_path / "runtime"),
            runtime_subdirectory="ratelimit",
            use_statsd=False,
        )
        runner = Runner(settings)
        runner.run_background()
        assert runner.wait_ready(15)
        try:
            url = f"http://127.0.0.1:{settings.port}/json"
            body = json.dumps(
                {
                    "domain": "it",
                    "descriptors": [{"entries": [{"key": "r", "value": "z"}]}],
                }
            ).encode()

            def call() -> int:
                req = urllib.request.Request(
                    url, data=body, headers={"Content-Type": "application/json"}
                )
                try:
                    with urllib.request.urlopen(req, timeout=10) as resp:
                        return resp.status
                except urllib.error.HTTPError as e:
                    return e.code

            assert [call() for i in range(4)] == [200, 200, 429, 429]
        finally:
            runner.stop()
