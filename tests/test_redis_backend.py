"""Redis backend tests against the in-process fake server — the twin of
test/redis/driver_impl_test.go (miniredis scenarios: refused connection,
auth, pipelines) and test/redis/fixed_cache_impl_test.go (exact wire
commands, window math, per-second routing, local-cache short-circuit,
jitter)."""

import random
import threading

import pytest

from api_ratelimit_tpu.backends.redis import RedisRateLimitCache
from api_ratelimit_tpu.backends.redis_driver import (
    RedisClient,
    RedisClusterClient,
    RedisError,
    key_slot,
)
from api_ratelimit_tpu.limiter.base_limiter import BaseRateLimiter
from api_ratelimit_tpu.limiter.local_cache import LocalCache
from api_ratelimit_tpu.models.config import RateLimit, new_rate_limit_stats
from api_ratelimit_tpu.models.descriptors import Descriptor, RateLimitRequest
from api_ratelimit_tpu.models.response import Code
from api_ratelimit_tpu.models.units import Unit
from api_ratelimit_tpu.stats import Store, TestSink
from api_ratelimit_tpu.testing.fake_redis import FakeRedisServer
from api_ratelimit_tpu.utils import FakeTimeSource


@pytest.fixture
def fake_redis():
    server = FakeRedisServer()
    yield server
    server.close()


def make_limit(scope, requests_per_unit, unit, key="k_v"):
    return RateLimit(
        full_key=key,
        limit=__import__(
            "api_ratelimit_tpu.models.response", fromlist=["RateLimitValue"]
        ).RateLimitValue(requests_per_unit, unit),
        stats=new_rate_limit_stats(scope, key),
    )


class TestDriver:
    def test_connection_refused(self):
        with pytest.raises(RedisError, match="dial failed"):
            RedisClient("tcp", "127.0.0.1:1", pool_size=1)

    def test_ping_on_startup_and_do_cmd(self, fake_redis):
        client = RedisClient("tcp", fake_redis.addr, pool_size=2)
        assert client.do_cmd("SET", "a", "1") == "OK"
        assert client.do_cmd("INCRBY", "a", 4) == 5
        client.close()

    def test_auth_fail_and_pass(self):
        server = FakeRedisServer(password="hunter2")
        try:
            with pytest.raises(RedisError, match="auth failed"):
                RedisClient("tcp", server.addr, pool_size=1, auth="wrong")
            client = RedisClient("tcp", server.addr, pool_size=1, auth="hunter2")
            assert client.do_cmd("PING") == "PONG"
            client.close()
        finally:
            server.close()

    def test_no_auth_when_required(self):
        server = FakeRedisServer(password="hunter2")
        try:
            with pytest.raises(RedisError, match="NOAUTH"):
                RedisClient("tcp", server.addr, pool_size=1)
        finally:
            server.close()

    def test_pipe_do_one_rtt(self, fake_redis):
        client = RedisClient("tcp", fake_redis.addr, pool_size=1)
        replies = client.pipe_do(
            [("INCRBY", "x", 1), ("EXPIRE", "x", 60), ("INCRBY", "x", 2)]
        )
        assert replies == [1, 1, 3]
        client.close()

    def test_implicit_pipelining_coalesces(self, fake_redis):
        """window/limit knobs enable cross-request coalescing
        (driver_impl.go:84-90)."""
        client = RedisClient(
            "tcp",
            fake_redis.addr,
            pool_size=1,
            pipeline_window_seconds=0.005,
            pipeline_limit=64,
        )
        assert client.implicit_pipelining_enabled()
        results = {}

        def call(i):
            results[i] = client.pipe_do([("INCRBY", f"key{i}", 1)])[0]

        threads = [threading.Thread(target=call, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(results[i] == 1 for i in range(8))
        client.close()

    def test_pipe_do_error_surfaces(self, fake_redis):
        client = RedisClient("tcp", fake_redis.addr, pool_size=1)
        with pytest.raises(RedisError, match="unknown command"):
            client.pipe_do([("NOSUCH", "k")])
        client.close()

    def test_sentinel_resolution(self, fake_redis):
        """Sentinel reports the fake as master; client transparently
        connects to it (driver_impl.go:111-116)."""
        sentinel = FakeRedisServer(
            sentinel_master=("mymaster", "127.0.0.1", fake_redis.port)
        )
        try:
            client = RedisClient(
                "tcp",
                f"mymaster,{sentinel.addr}",
                pool_size=1,
                redis_type="SENTINEL",
            )
            assert client.do_cmd("INCRBY", "s", 7) == 7
            assert fake_redis.get_int("s") == 7
            client.close()
        finally:
            sentinel.close()

    def test_cluster_topology(self, fake_redis):
        client = RedisClusterClient(fake_redis.addr, pool_size=1)
        replies = client.pipe_do([("INCRBY", "ck", 3), ("EXPIRE", "ck", 60)])
        assert replies == [3, 1]
        client.close()

    def test_key_slot_hash_tags(self):
        assert key_slot("{user}.a") == key_slot("{user}.b")
        assert 0 <= key_slot("anything") < 16384


class TestRedisFixedCache:
    def _setup(self, fake_redis, local_cache=None, jitter_max=0, per_second=None):
        store = Store(TestSink())
        scope = store.scope("ratelimit").scope("service").scope("rate_limit")
        time_source = FakeTimeSource(now=1234)
        base = BaseRateLimiter(
            time_source=time_source,
            jitter_rand=random.Random(0),
            expiration_jitter_max_seconds=jitter_max,
            local_cache=local_cache,
            near_limit_ratio=0.8,
        )
        client = RedisClient("tcp", fake_redis.addr, pool_size=2)
        cache = RedisRateLimitCache(client, base, per_second_client=per_second)
        return cache, scope, time_source

    def test_exact_wire_commands(self, fake_redis):
        """INCRBY domain_key_value_1234 1 + EXPIRE ... 1 — the exact wire
        assertion from fixed_cache_impl_test.go:59-64 (window snap of
        now=1234 with SECOND unit -> suffix 1234, TTL = divider)."""
        cache, scope, _ = self._setup(fake_redis)
        limit = make_limit(scope, 10, Unit.SECOND, "key_value")
        req = RateLimitRequest(
            domain="domain", descriptors=(Descriptor.of(("key", "value")),)
        )
        resp = cache.do_limit(req, [limit])
        assert resp.descriptor_statuses[0].code == Code.OK
        assert resp.descriptor_statuses[0].limit_remaining == 9
        seen = [c for c in fake_redis.commands_seen if c[0] != b"PING"]
        assert seen == [
            [b"INCRBY", b"domain_key_value_1234", b"1"],
            [b"EXPIRE", b"domain_key_value_1234", b"1"],
        ]
        assert fake_redis.get_int("domain_key_value_1234") == 1

    def test_window_snap_minute(self, fake_redis):
        cache, scope, _ = self._setup(fake_redis)
        limit = make_limit(scope, 10, Unit.MINUTE, "key_value")
        req = RateLimitRequest(
            domain="domain", descriptors=(Descriptor.of(("key", "value")),)
        )
        cache.do_limit(req, [limit])
        # 1234 // 60 * 60 = 1200; TTL = 60
        assert fake_redis.get_int("domain_key_value_1200") == 1
        assert 59 <= fake_redis.ttl("domain_key_value_1200") <= 60

    def test_over_limit_and_stats(self, fake_redis):
        cache, scope, _ = self._setup(fake_redis)
        limit = make_limit(scope, 2, Unit.SECOND, "k_v")
        req = RateLimitRequest(domain="d", descriptors=(Descriptor.of(("k", "v")),))
        codes = [cache.do_limit(req, [limit]).descriptor_statuses[0].code for _ in range(4)]
        assert codes == [Code.OK, Code.OK, Code.OVER_LIMIT, Code.OVER_LIMIT]
        assert limit.stats.total_hits.value() == 4
        assert limit.stats.over_limit.value() == 2

    def test_hits_addend(self, fake_redis):
        cache, scope, _ = self._setup(fake_redis)
        limit = make_limit(scope, 10, Unit.SECOND, "k_v")
        req = RateLimitRequest(
            domain="d", descriptors=(Descriptor.of(("k", "v")),), hits_addend=5
        )
        resp = cache.do_limit(req, [limit])
        assert resp.descriptor_statuses[0].limit_remaining == 5
        resp = cache.do_limit(req, [limit])
        assert resp.descriptor_statuses[0].code == Code.OK
        assert resp.descriptor_statuses[0].limit_remaining == 0

    def test_nil_limit_skips_backend(self, fake_redis):
        cache, scope, _ = self._setup(fake_redis)
        req = RateLimitRequest(domain="d", descriptors=(Descriptor.of(("k", "v")),))
        resp = cache.do_limit(req, [None])
        assert resp.descriptor_statuses[0].code == Code.OK
        assert resp.descriptor_statuses[0].current_limit is None
        assert [c for c in fake_redis.commands_seen if c[0] != b"PING"] == []

    def test_local_cache_short_circuits_redis(self, fake_redis):
        """Once a key is known over-limit, no redis commands are issued for
        it (.Times(0) assertion, fixed_cache_impl_test.go:175-276)."""
        time_source = FakeTimeSource(now=1234)
        local = LocalCache(max_entries=100, time_source=time_source)
        cache, scope, _ = self._setup(fake_redis, local_cache=local)
        limit = make_limit(scope, 1, Unit.SECOND, "k_v")
        req = RateLimitRequest(domain="d", descriptors=(Descriptor.of(("k", "v")),))
        assert cache.do_limit(req, [limit]).descriptor_statuses[0].code == Code.OK
        assert (
            cache.do_limit(req, [limit]).descriptor_statuses[0].code
            == Code.OVER_LIMIT
        )
        fake_redis.commands_seen.clear()
        resp = cache.do_limit(req, [limit])
        assert resp.descriptor_statuses[0].code == Code.OVER_LIMIT
        assert fake_redis.commands_seen == []  # served from local cache
        assert limit.stats.over_limit_with_local_cache.value() == 1

    def test_jitter_extends_ttl(self, fake_redis):
        """EXPIRE = divider + Int63n(jitter_max) with seeded rand
        (fixed_cache_impl_test.go:451+)."""
        cache, scope, _ = self._setup(fake_redis, jitter_max=300)
        expected_jitter = random.Random(0).randrange(300)
        limit = make_limit(scope, 10, Unit.SECOND, "k_v")
        req = RateLimitRequest(domain="d", descriptors=(Descriptor.of(("k", "v")),))
        cache.do_limit(req, [limit])
        expire = [c for c in fake_redis.commands_seen if c[0] == b"EXPIRE"][0]
        assert int(expire[2]) == 1 + expected_jitter

    def test_per_second_pool_routing(self, fake_redis):
        """SECOND-unit keys go to the per-second client; others to main
        (fixed_cache_impl_test.go:26-29)."""
        second_server = FakeRedisServer()
        try:
            per_second = RedisClient("tcp", second_server.addr, pool_size=1)
            cache, scope, _ = self._setup(fake_redis, per_second=per_second)
            limits = [
                make_limit(scope, 10, Unit.SECOND, "sec"),
                make_limit(scope, 10, Unit.MINUTE, "min"),
            ]
            req = RateLimitRequest(
                domain="d",
                descriptors=(
                    Descriptor.of(("sec", "s")),
                    Descriptor.of(("min", "m")),
                ),
            )
            resp = cache.do_limit(req, limits)
            assert [s.code for s in resp.descriptor_statuses] == [Code.OK, Code.OK]
            assert second_server.get_int("d_sec_s_1234") == 1
            assert fake_redis.get_int("d_min_m_1200") == 1
            assert second_server.get_int("d_min_m_1200") is None
            assert fake_redis.get_int("d_sec_s_1234") is None
        finally:
            second_server.close()

    def test_redis_down_raises_cache_error(self, fake_redis):
        cache, scope, _ = self._setup(fake_redis)
        limit = make_limit(scope, 10, Unit.SECOND, "k_v")
        req = RateLimitRequest(domain="d", descriptors=(Descriptor.of(("k", "v")),))
        fake_redis.close()
        with pytest.raises(RedisError):
            cache.do_limit(req, [limit])


class TestRedisVsMemoryOracle:
    def test_differential_random_stream(self, fake_redis):
        """The redis backend and the in-process memory oracle must agree
        decision-for-decision on a random stream (SURVEY.md §4.4)."""
        from api_ratelimit_tpu.backends.memory import MemoryRateLimitCache

        rng = random.Random(42)
        store = Store(TestSink())
        scope_a = store.scope("a")
        scope_b = store.scope("b")
        time_source = FakeTimeSource(now=5000)

        def base():
            return BaseRateLimiter(
                time_source=time_source,
                jitter_rand=random.Random(0),
                expiration_jitter_max_seconds=0,
                local_cache=None,
                near_limit_ratio=0.8,
            )

        redis_cache = RedisRateLimitCache(
            RedisClient("tcp", fake_redis.addr, pool_size=2), base()
        )
        oracle = MemoryRateLimitCache(base())

        limits_a = {
            key: make_limit(scope_a, rpu, unit, key)
            for key, rpu, unit in [
                ("u1", 3, Unit.SECOND),
                ("u2", 5, Unit.MINUTE),
                ("u3", 2, Unit.HOUR),
            ]
        }
        limits_b = {
            key: make_limit(scope_b, limit.limit.requests_per_unit, limit.limit.unit, key)
            for key, limit in limits_a.items()
        }

        for step in range(200):
            if rng.random() < 0.2:
                time_source.advance(rng.randrange(0, 3))
            key = rng.choice(list(limits_a))
            value = rng.choice(["x", "y"])
            req = RateLimitRequest(
                domain="diff", descriptors=(Descriptor.of((key, value)),)
            )
            got = redis_cache.do_limit(req, [limits_a[key]]).descriptor_statuses[0]
            want = oracle.do_limit(req, [limits_b[key]]).descriptor_statuses[0]
            assert (got.code, got.limit_remaining) == (
                want.code,
                want.limit_remaining,
            ), f"divergence at step {step} key {key}"


class TestRespParserRobustness:
    """Corrupt server replies must surface as RedisError (the counted
    backend-failure path), never raw ValueError/UnicodeDecodeError/
    unbounded allocation — the parser is in-repo (no radix to lean on)."""

    @staticmethod
    def _reader_for(payload: bytes):
        import socket as socket_mod

        from api_ratelimit_tpu.backends.redis_driver import _Reader

        a, b = socket_mod.socketpair()
        a.sendall(payload)
        a.close()  # EOF after payload: parser must not hang
        b.settimeout(5)
        return _Reader(b)

    def test_corrupt_bulk_length(self):
        from api_ratelimit_tpu.backends.redis_driver import RedisError

        r = self._reader_for(b"$abc\r\n")
        with pytest.raises(RedisError, match="bad RESP length"):
            r.read_reply()

    def test_corrupt_integer(self):
        from api_ratelimit_tpu.backends.redis_driver import RedisError

        r = self._reader_for(b":12x\r\n")
        with pytest.raises(RedisError, match="bad RESP length"):
            r.read_reply()

    def test_huge_bulk_length_rejected(self):
        from api_ratelimit_tpu.backends.redis_driver import RedisError

        r = self._reader_for(b"$99999999999\r\n")
        with pytest.raises(RedisError, match="bad RESP bulk length"):
            r.read_reply()

    def test_negative_array_length_rejected(self):
        from api_ratelimit_tpu.backends.redis_driver import RedisError

        r = self._reader_for(b"*-7\r\n")
        with pytest.raises(RedisError, match="bad RESP array length"):
            r.read_reply()

    def test_invalid_utf8_status_survives(self):
        r = self._reader_for(b"+\xff\xfe\r\n")
        assert isinstance(r.read_reply(), str)

    def test_valid_replies_still_parse(self):
        r = self._reader_for(b"+OK\r\n:42\r\n$3\r\nfoo\r\n*2\r\n:1\r\n:2\r\n$-1\r\n")
        assert r.read_reply() == "OK"
        assert r.read_reply() == 42
        assert r.read_reply() == b"foo"
        assert r.read_reply() == [1, 2]
        assert r.read_reply() is None
