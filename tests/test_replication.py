"""Warm-standby device-owner replication (persist/replication.py).

Covers the frame codec (CRC, sequence, sections), the dirty-set diff, the
in-process primary -> standby stream (snapshot then deltas), epoch-fenced
promotion with the boot-style reconcile + lease floors, the client-driven
failover in SidecarEngineClient (breaker/exhaustion/stale-epoch), the
split-brain guard (pinned stale_epoch_rejected), the repl.degraded health
probe on both roles, and the single-address byte-identical rollback arm.
"""

from __future__ import annotations

import socket
import struct
import threading
import time

import numpy as np
import pytest

from api_ratelimit_tpu.backends.sidecar import (
    FLAG_EPOCH,
    MAGIC,
    OP_SUBMIT,
    STATUS_STALE_EPOCH,
    VERSION,
    SidecarEngineClient,
    SlabSidecarServer,
    _HDR,
    _recv_exact,
    encode_items,
)
from api_ratelimit_tpu.backends.tpu import SlabDeviceEngine, _Item
from api_ratelimit_tpu.limiter.cache import CacheError
from api_ratelimit_tpu.persist import replication as repl_mod
from api_ratelimit_tpu.persist.replication import (
    KIND_DELTA,
    KIND_SNAPSHOT,
    ReplProtocolError,
    ReplicationCoordinator,
    diff_tables,
    encode_frame,
    pack_delta_payload,
    pack_snapshot_payload,
    read_frame,
    unpack_delta_payload,
    unpack_snapshot_payload,
)
from api_ratelimit_tpu.persist.snapshot import (
    LEASE_ROW_WIDTH,
    ROW_WIDTH,
)
from api_ratelimit_tpu.testing.faults import FaultInjector, parse_fault_spec
from api_ratelimit_tpu.utils import FakeTimeSource
from api_ratelimit_tpu.utils.timeutil import RealTimeSource

NOW = 1_700_000_000


def _reader(blob: bytes):
    pos = [0]

    def recv(n: int) -> bytes:
        chunk = blob[pos[0] : pos[0] + n]
        pos[0] += n
        return chunk

    return recv


def _make_engine(ts=None, n_slots=1 << 10):
    return SlabDeviceEngine(
        time_source=ts or RealTimeSource(),
        n_slots=n_slots,
        buckets=(128,),
        max_batch=1024,
        use_pallas=False,
        block_mode=True,
    )


def _items(fp=42, hits=1, limit=1_000_000, divider=3600):
    return [_Item(fp=fp, hits=hits, limit=limit, divider=divider, jitter=0)]


class TestFrameCodec:
    def test_frame_round_trip(self):
        payload = b"hello replication"
        blob = encode_frame(KIND_DELTA, epoch=7, seq=123, payload=payload)
        kind, epoch, seq, got = read_frame(_reader(blob))
        assert (kind, epoch, seq, got) == (KIND_DELTA, 7, 123, payload)

    def test_corrupt_payload_fails_crc(self):
        blob = bytearray(encode_frame(KIND_DELTA, 1, 1, b"x" * 64))
        blob[repl_mod._FRAME_HDR.size + 10] ^= 0xFF
        with pytest.raises(ReplProtocolError, match="CRC"):
            read_frame(_reader(bytes(blob)))

    def test_bad_magic_and_kind_rejected(self):
        blob = bytearray(encode_frame(KIND_SNAPSHOT, 1, 1, b""))
        blob[0] ^= 0xFF
        with pytest.raises(ReplProtocolError, match="magic"):
            read_frame(_reader(bytes(blob)))
        blob = bytearray(encode_frame(KIND_SNAPSHOT, 1, 1, b""))
        blob[4] = 99
        with pytest.raises(ReplProtocolError, match="kind"):
            read_frame(_reader(bytes(blob)))

    def test_snapshot_payload_round_trip(self):
        table = np.arange(8 * ROW_WIDTH, dtype=np.uint32).reshape(
            8, ROW_WIDTH
        )
        lease = np.ones((3, LEASE_ROW_WIDTH), dtype=np.uint32)
        payload = pack_snapshot_payload([table], lease, NOW, ways=4)
        tables, headers, lease_rows = unpack_snapshot_payload(payload)
        assert len(tables) == 1
        assert (tables[0] == table).all()
        assert headers[0].ways == 4
        assert headers[0].n_slots == 8
        assert (lease_rows == lease).all()

    def test_snapshot_section_corruption_detected(self):
        table = np.arange(8 * ROW_WIDTH, dtype=np.uint32).reshape(
            8, ROW_WIDTH
        )
        payload = bytearray(
            pack_snapshot_payload(
                [table], np.zeros((0, LEASE_ROW_WIDTH), np.uint32), NOW
            )
        )
        payload[-5] ^= 0xFF  # inside a section payload
        with pytest.raises(ReplProtocolError):
            unpack_snapshot_payload(bytes(payload))

    def test_delta_payload_round_trip(self):
        idxs = np.array([1, 5, 7], dtype=np.int64)
        rows = np.arange(3 * ROW_WIDTH, dtype=np.uint32).reshape(
            3, ROW_WIDTH
        )
        lease = np.full((2, LEASE_ROW_WIDTH), 9, dtype=np.uint32)
        payload = pack_delta_payload([(0, idxs, rows)], lease)
        dirty, lease_rows = unpack_delta_payload(payload, ROW_WIDTH)
        assert dirty[0][0] == 0
        assert (dirty[0][1] == idxs).all()
        assert (dirty[0][2] == rows).all()
        assert (lease_rows == lease).all()

    def test_empty_delta_is_a_valid_heartbeat(self):
        payload = pack_delta_payload(
            [], np.zeros((0, LEASE_ROW_WIDTH), np.uint32)
        )
        dirty, lease_rows = unpack_delta_payload(payload, ROW_WIDTH)
        assert dirty == [] and lease_rows.shape[0] == 0

    def test_truncated_delta_rejected(self):
        idxs = np.array([1], dtype=np.int64)
        rows = np.zeros((1, ROW_WIDTH), dtype=np.uint32)
        payload = pack_delta_payload(
            [(0, idxs, rows)], np.zeros((0, LEASE_ROW_WIDTH), np.uint32)
        )
        with pytest.raises(ReplProtocolError):
            unpack_delta_payload(payload[:-3], ROW_WIDTH)

    def test_diff_tables_finds_exactly_the_changed_rows(self):
        prev = np.zeros((16, ROW_WIDTH), dtype=np.uint32)
        cur = prev.copy()
        cur[3, 2] = 7
        cur[11] = 5
        idxs, rows = diff_tables(prev, cur)
        assert idxs.tolist() == [3, 11]
        assert (rows == cur[[3, 11]]).all()
        idxs, _ = diff_tables(cur, cur)
        assert idxs.size == 0


class _Cluster:
    """One in-process primary + standby pair over unix sockets."""

    def __init__(self, tmp_path, interval_ms=25.0, faults_p=None, faults_s=None):
        self.p_sock = str(tmp_path / "p.sock")
        self.s_sock = str(tmp_path / "s.sock")
        self.p_engine = _make_engine()
        self.p_coord = ReplicationCoordinator(
            self.p_engine,
            "primary",
            interval_ms=interval_ms,
            fault_injector=faults_p,
        )
        self.p_server = SlabSidecarServer(
            self.p_sock, self.p_engine, repl=self.p_coord
        )
        self.p_coord.start()
        self.s_engine = _make_engine()
        self.s_coord = ReplicationCoordinator(
            self.s_engine,
            "standby",
            peer_address=self.p_sock,
            interval_ms=interval_ms,
            fault_injector=faults_s,
        )
        self.s_server = SlabSidecarServer(
            self.s_sock, self.s_engine, repl=self.s_coord
        )
        self.s_coord.start()
        self.closed = set()

    def client(self, **kw):
        kw.setdefault("retries", 2)
        kw.setdefault("retry_backoff", 0.001)
        kw.setdefault("retry_backoff_max", 0.01)
        kw.setdefault("breaker_threshold", 2)
        kw.setdefault("breaker_reset", 0.05)
        return SidecarEngineClient([self.p_sock, self.s_sock], **kw)

    def wait_applied(self, n, timeout=10.0):
        deadline = time.monotonic() + timeout
        while self.s_coord.frames_applied_total < n:
            assert time.monotonic() < deadline, (
                f"standby stuck at {self.s_coord.frames_applied_total} "
                f"applied frames (wanted {n})"
            )
            time.sleep(0.01)

    def wait_synced_count(self, fp, count, timeout=10.0):
        """Wait until the standby's replica holds `count` for `fp`."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            tables, _, _ = self.s_coord.replica_state()
            if tables is not None:
                rows = tables[0]
                hit = rows[rows[:, 0] == (fp & 0xFFFFFFFF)]
                if hit.shape[0] and int(hit[0, 2]) == count:
                    return
            time.sleep(0.01)
        raise AssertionError(f"standby never saw count {count} for fp {fp}")

    def kill_primary(self):
        if "p" not in self.closed:
            self.closed.add("p")
            self.p_server.close()
            self.p_coord.close()

    def close(self):
        self.kill_primary()
        if "s" not in self.closed:
            self.closed.add("s")
            self.s_server.close()
            self.s_coord.close()


@pytest.fixture
def cluster(tmp_path):
    c = _Cluster(tmp_path)
    yield c
    c.close()


class TestStreamAndPromotion:
    def test_standby_mirrors_traffic_then_promotion_continues_counters(
        self, cluster
    ):
        client = cluster.client()
        try:
            for i in range(10):
                assert client.submit(_items()) == [i + 1]
            # quiesce, then wait until the replica holds the full count —
            # convergence, not just "a frame arrived"
            cluster.wait_synced_count(42, 10)
            cluster.kill_primary()
            # zero failed requests: the next write fails over, promotes
            # the standby, and CONTINUES the replicated counter
            assert client.submit(_items()) == [11]
            assert cluster.s_coord.role == "primary"
            assert cluster.s_coord.epoch == 2
            assert cluster.s_coord.promotions_total == 1
            assert client.submit(_items()) == [12]
        finally:
            client.close()

    def test_promotion_drops_dead_rows(self, tmp_path):
        """The boot-style reconcile: rows whose window ended (and TTL
        passed) on the replica do not survive promotion."""
        ts = FakeTimeSource(NOW)
        engine = _make_engine(ts)
        coord = ReplicationCoordinator(
            engine,
            "standby",
            peer_address="/nonexistent",
            interval_ms=10,
            time_source=ts,
        )
        table = np.zeros((1 << 10, ROW_WIDTH), dtype=np.uint32)
        # a live row: window open, TTL ahead
        table[5] = (7, 0, 3, NOW - NOW % 3600, NOW + 600, 3600, 0, 0)
        # a dead row: TTL passed
        table[9] = (8, 0, 9, NOW - 7200, NOW - 100, 3600, 0, 0)
        # ways=0 (an "unknown layout" writer): promotion must rehash the
        # surviving rows into this engine's set geometry
        payload = pack_snapshot_payload(
            [table],
            np.zeros((0, LEASE_ROW_WIDTH), np.uint32),
            NOW,
            ways=0,
        )
        coord._apply_frame(KIND_SNAPSHOT, 1, 1, payload)
        assert coord.promote(reason="test") is True
        assert coord.promote(reason="twice") is False  # idempotent
        afters = engine.submit_block(
            np.array(
                [[7, 8], [0, 0], [1, 1], [100, 100], [3600, 3600], [0, 0]],
                dtype=np.uint32,
            )
        )
        # live row continued at 3 -> 4; dead row restarted at 1
        assert afters.tolist() == [4, 1]
        coord.close()

    def test_promotion_applies_lease_floors(self, tmp_path):
        """A replica slab older than a replicated grant must restore the
        counter AT the grant watermark — never double-grant."""
        ts = FakeTimeSource(NOW)
        engine = _make_engine(ts)
        coord = ReplicationCoordinator(
            engine,
            "standby",
            peer_address="/nonexistent",
            interval_ms=10,
            time_source=ts,
        )
        window = NOW - NOW % 3600
        table = np.zeros((1 << 10, ROW_WIDTH), dtype=np.uint32)
        # slab shows count 2, but a live liability floors it at 12
        table[3] = (21, 0, 2, window, NOW + 600, 3600, 0, 0)
        lease = np.zeros((1, LEASE_ROW_WIDTH), dtype=np.uint32)
        lease[0] = (21, 0, window, 10, 0, 12, NOW + 300, 0)
        payload = pack_snapshot_payload([table], lease, NOW, ways=0)
        coord._apply_frame(KIND_SNAPSHOT, 1, 1, payload)
        coord.promote(reason="test")
        afters = engine.submit_block(
            np.array(
                [[21], [0], [1], [1000], [3600], [0]], dtype=np.uint32
            )
        )
        assert afters.tolist() == [13]  # floored at 12, then +1
        _entries, tokens = engine.lease_registry.outstanding()
        assert tokens == 10  # the liability itself was re-seeded
        coord.close()

    def test_delta_sequence_gap_raises(self, tmp_path):
        ts = FakeTimeSource(NOW)
        engine = _make_engine(ts)
        coord = ReplicationCoordinator(
            engine, "standby", peer_address="/nonexistent", interval_ms=10
        )
        table = np.zeros((1 << 10, ROW_WIDTH), dtype=np.uint32)
        payload = pack_snapshot_payload(
            [table], np.zeros((0, LEASE_ROW_WIDTH), np.uint32), NOW
        )
        coord._apply_frame(KIND_SNAPSHOT, 1, 1, payload)
        delta = pack_delta_payload(
            [], np.zeros((0, LEASE_ROW_WIDTH), np.uint32)
        )
        coord._apply_frame(KIND_DELTA, 1, 2, delta)
        with pytest.raises(ReplProtocolError, match="gap"):
            coord._apply_frame(KIND_DELTA, 1, 4, delta)
        coord.close()

    def test_geometry_mismatch_is_a_loud_protocol_error(self, tmp_path):
        ts = FakeTimeSource(NOW)
        engine = _make_engine(ts, n_slots=1 << 10)
        coord = ReplicationCoordinator(
            engine, "standby", peer_address="/nonexistent", interval_ms=10
        )
        wrong = np.zeros((64, ROW_WIDTH), dtype=np.uint32)  # wrong n_slots
        payload = pack_snapshot_payload(
            [wrong], np.zeros((0, LEASE_ROW_WIDTH), np.uint32), NOW
        )
        with pytest.raises(ReplProtocolError, match="geometry"):
            coord._apply_frame(KIND_SNAPSHOT, 1, 1, payload)
        coord.close()


class TestSplitBrainGuard:
    def test_stale_primary_write_rejected_and_counted(self, cluster):
        """The pinned acceptance: a resurrected old primary rejects a
        write fenced on the promoted epoch, stale_epoch_rejected > 0, and
        the increment is NOT applied."""
        client = cluster.client()
        try:
            client.submit(_items())
            cluster.wait_synced_count(42, 1)
            cluster.kill_primary()
            assert client.submit(_items()) == [2]  # promoted standby
            assert client._epoch_known == 2

            # resurrect the old primary at the same address, epoch 1
            p2_engine = _make_engine()
            p2_coord = ReplicationCoordinator(
                p2_engine, "primary", interval_ms=25
            )
            p2_server = SlabSidecarServer(
                cluster.p_sock, p2_engine, repl=p2_coord
            )
            try:
                # a raw epoch-fenced write straight at the stale primary
                conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                conn.connect(cluster.p_sock)
                payload = encode_items(_items())
                conn.sendall(
                    _HDR.pack(MAGIC, VERSION, OP_SUBMIT, FLAG_EPOCH)
                    + payload
                    + struct.pack("<I", client._epoch_known)
                )
                status = _recv_exact(conn, 1)
                assert status == bytes([STATUS_STALE_EPOCH])
                (srv_epoch,) = struct.unpack("<I", _recv_exact(conn, 4))
                assert srv_epoch == 1
                conn.close()
                assert p2_coord.stale_epoch_rejected_total > 0
                # the write never touched the stale slab
                tables = p2_engine.export_tables()
                assert (tables[0][:, 0] == 42).sum() == 0
            finally:
                p2_server.close()
                p2_coord.close()
        finally:
            client.close()

    def test_repl_less_server_answers_epoch_zero(self, tmp_path):
        """A FLAG_EPOCH frame at a replication-less owner still works —
        the epoch answers 0 and the client ignores it."""
        engine = _make_engine()
        sock = str(tmp_path / "plain.sock")
        server = SlabSidecarServer(sock, engine)
        other = str(tmp_path / "other.sock")
        other_server = SlabSidecarServer(other, _make_engine())
        client = SidecarEngineClient(
            [sock, other], retries=0, breaker_threshold=0
        )
        try:
            assert client.submit(_items()) == [1]
            assert client._epoch_known == 0
        finally:
            client.close()
            server.close()
            other_server.close()


class TestClientFailover:
    def test_exhausted_retries_fail_over_with_zero_failures(self, cluster):
        client = cluster.client(retries=1)
        try:
            assert client.submit(_items()) == [1]
            cluster.wait_synced_count(42, 1)
            cluster.kill_primary()
            # every subsequent submit succeeds against the standby
            for i in range(5):
                assert client.submit(_items()) == [i + 2]
            assert client.active_address == cluster.s_sock
            assert client.failover_reason() is not None
            assert "standby" in client.failover_reason()
        finally:
            client.close()

    def test_breaker_open_triggers_failover_instead_of_fail_fast(
        self, cluster
    ):
        client = cluster.client(retries=0, breaker_threshold=1)
        try:
            client.submit(_items())
            cluster.wait_synced_count(42, 1)
            cluster.kill_primary()
            # first call exhausts retries (failing over inside the call);
            # any later call must not fail fast on an open breaker
            for i in range(3):
                assert client.submit(_items()) == [i + 2]
        finally:
            client.close()

    def test_failover_journey_flag_retained(self, cluster, test_store):
        from api_ratelimit_tpu.tracing import journeys

        store, _ = test_store
        recorder = journeys.JourneyRecorder(
            slow_ms=1e9, retain=8, ring=8
        )
        journeys.set_global_recorder(recorder)
        client = cluster.client()
        try:
            client.submit(_items())
            cluster.wait_synced_count(42, 1)
            cluster.kill_primary()
            journey = recorder.begin("request")
            client.submit(_items())
            recorder.finish(journey, 1.0)
            retained = recorder.retained()
            assert retained, "failover journey was not tail-sampled"
            assert journeys.FLAG_FAILOVER in retained[-1].flags
        finally:
            journeys.set_global_recorder(None)
            client.close()

    def test_failover_counter_and_gauge(self, cluster, test_store):
        store, _ = test_store
        client = cluster.client(scope=store.scope("ratelimit"))
        try:
            client.submit(_items())
            cluster.wait_synced_count(42, 1)
            cluster.kill_primary()
            client.submit(_items())
            snap = store.debug_snapshot()
            assert snap["ratelimit.sidecar.failover"] >= 1
            assert snap["ratelimit.sidecar.active_backend"] == 1
        finally:
            client.close()


class TestRollbackArm:
    """REPL_ROLE unset / single-address == the pre-replication protocol,
    byte for byte (the same discipline as HOST_FAST_PATH/DISPATCH_LOOP)."""

    def _capture_frame(self, tmp_path, address_arg):
        """Boot a client against a capturing server; returns the raw
        SUBMIT frame bytes the client sent."""
        captured = []
        done = threading.Event()
        sock_path = str(tmp_path / "cap.sock")
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(sock_path)
        srv.listen(4)

        def serve():
            try:
                while not done.is_set():
                    conn, _ = srv.accept()
                    with conn:
                        while True:
                            hdr = _recv_exact(conn, _HDR.size)
                            magic, version, op, flags = _HDR.unpack(hdr)
                            if op == 2:  # PING
                                conn.sendall(b"\x00")
                                continue
                            body = b""
                            # read the item block
                            n_raw = _recv_exact(conn, 4)
                            (n,) = struct.unpack("<I", n_raw)
                            body = n_raw + _recv_exact(conn, 6 * n * 4)
                            if flags & FLAG_EPOCH:
                                body += _recv_exact(conn, 4)
                            captured.append(hdr + body)
                            out = np.ones(n, dtype=np.uint32)
                            if flags & FLAG_EPOCH:
                                conn.sendall(
                                    b"\x02"
                                    + struct.pack("<I", 0)
                                    + struct.pack("<I", n)
                                    + out.tobytes()
                                )
                            else:
                                conn.sendall(
                                    b"\x00"
                                    + struct.pack("<I", n)
                                    + out.tobytes()
                                )
            except (OSError, ConnectionError):
                return

        thread = threading.Thread(target=serve, daemon=True)
        thread.start()
        client = SidecarEngineClient(
            address_arg, retries=0, breaker_threshold=0
        )
        try:
            client.submit(_items())
        finally:
            client.close()
            done.set()
            srv.close()
        return captured[-1]

    def test_single_address_frames_are_byte_identical_legacy(self, tmp_path):
        frame = self._capture_frame(tmp_path, str(tmp_path / "cap.sock"))
        expected = (
            _HDR.pack(MAGIC, VERSION, OP_SUBMIT, 0) + encode_items(_items())
        )
        assert frame == expected

    def test_single_entry_list_is_also_legacy(self, tmp_path):
        frame = self._capture_frame(tmp_path, [str(tmp_path / "cap.sock")])
        expected = (
            _HDR.pack(MAGIC, VERSION, OP_SUBMIT, 0) + encode_items(_items())
        )
        assert frame == expected

    def test_multi_address_sets_the_epoch_flag(self, tmp_path):
        frame = self._capture_frame(
            tmp_path,
            [str(tmp_path / "cap.sock"), str(tmp_path / "unused.sock")],
        )
        _magic, _version, _op, flags = _HDR.unpack(frame[: _HDR.size])
        assert flags & FLAG_EPOCH
        # fixed u32 epoch trailer rides after the block
        assert len(frame) == _HDR.size + len(encode_items(_items())) + 4


class TestDegradedProbes:
    def test_primary_without_standby_reports_degraded_after_grace(self):
        engine = _make_engine()
        coord = ReplicationCoordinator(
            engine, "primary", interval_ms=10.0, max_lag_ms=30.0
        )
        coord.start()
        try:
            assert coord.degraded_reason() is None  # boot grace
            time.sleep(0.05)
            reason = coord.degraded_reason()
            assert reason is not None and "no standby" in reason
        finally:
            coord.close()

    def test_standby_stale_probe_raises_and_clears(self, tmp_path):
        cluster = _Cluster(tmp_path, interval_ms=20.0)
        try:
            cluster.wait_applied(1)
            # freshly applied: clear
            assert cluster.s_coord.degraded_reason() is None
            # primary stops shipping (killed): lag crosses 5x interval
            cluster.kill_primary()
            time.sleep(0.25)
            reason = cluster.s_coord.degraded_reason()
            assert reason is not None and "standby stale" in reason
        finally:
            cluster.close()

    def test_primary_with_standby_is_healthy(self, tmp_path):
        cluster = _Cluster(tmp_path, interval_ms=20.0)
        try:
            cluster.wait_applied(2)
            assert cluster.p_coord.degraded_reason() is None
        finally:
            cluster.close()

    def test_health_checker_integration(self, tmp_path):
        from api_ratelimit_tpu.server.health import HealthChecker

        cluster = _Cluster(tmp_path, interval_ms=20.0)
        try:
            cluster.wait_applied(1)
            health = HealthChecker(name="ratelimit-sidecar")
            health.add_degraded_probe(cluster.s_coord.degraded_reason)
            assert health.http_response() == (200, "OK")
            cluster.kill_primary()
            time.sleep(0.25)
            status, body = health.http_response()
            assert status == 200  # degraded never drains
            assert "repl.degraded" in body
        finally:
            cluster.close()


class TestResync:
    def test_ship_drop_fault_forces_resync_and_convergence(self, tmp_path):
        """repl.ship drop consumes sequence numbers without sending: the
        standby must detect the gap, resync off a fresh snapshot, and
        still converge on the primary's counters."""
        faults = FaultInjector(
            parse_fault_spec("repl.ship:drop:0.4"), seed=3
        )
        cluster = _Cluster(tmp_path, interval_ms=15.0, faults_p=faults)
        client = cluster.client()
        try:
            for _ in range(12):
                client.submit(_items())
            deadline = time.monotonic() + 10.0
            while cluster.s_coord.resyncs_total < 1:
                assert time.monotonic() < deadline, "no resync happened"
                time.sleep(0.01)
            faults.clear()  # outage ends; the stream heals
            cluster.wait_synced_count(42, 12)
        finally:
            client.close()
            cluster.close()

    def test_apply_corruption_forces_resync(self, tmp_path):
        class _OneShot(FaultInjector):
            def __init__(self):
                super().__init__(parse_fault_spec("repl.apply:torn_write:1.0"))
                self.shots = 1

            def fire(self, site):
                if self.shots <= 0:
                    return None
                action = super().fire(site)
                if action is not None:
                    self.shots -= 1
                return action

        faults = _OneShot()
        cluster = _Cluster(tmp_path, interval_ms=15.0, faults_s=faults)
        client = cluster.client()
        try:
            client.submit(_items())
            deadline = time.monotonic() + 10.0
            while cluster.s_coord.resyncs_total < 1:
                assert time.monotonic() < deadline, "no resync happened"
                time.sleep(0.01)
            cluster.wait_synced_count(42, 1)
        finally:
            client.close()
            cluster.close()

    def test_ship_delay_shows_up_as_primary_lag(self, tmp_path):
        faults = FaultInjector(
            parse_fault_spec("repl.ship:delay_ms:400")
        )
        cluster = _Cluster(tmp_path, interval_ms=20.0, faults_p=faults)
        try:
            # the first (snapshot) ship is itself delayed; by the time it
            # lands the next is already late — primary lag crosses 5x20ms
            time.sleep(0.3)
            reason = cluster.p_coord.degraded_reason()
            assert reason is not None and "repl.degraded" in reason
        finally:
            faults.clear()
            cluster.close()


class TestAutoRole:
    def test_auto_resolves_standby_when_peer_answers(self, tmp_path):
        cluster = _Cluster(tmp_path, interval_ms=20.0)
        auto_sock = str(tmp_path / "auto.sock")
        engine = _make_engine()
        coord = ReplicationCoordinator(
            engine, "auto", peer_address=cluster.p_sock, interval_ms=20.0
        )
        server = SlabSidecarServer(auto_sock, engine, repl=coord)
        try:
            coord.start()
            assert coord.role == "standby"
        finally:
            server.close()
            coord.close()
            cluster.close()

    def test_auto_resolves_primary_when_peer_dark(self, tmp_path):
        engine = _make_engine()
        coord = ReplicationCoordinator(
            engine,
            "auto",
            peer_address=str(tmp_path / "nobody.sock"),
            interval_ms=20.0,
        )
        try:
            coord.start()
            assert coord.role == "primary"
        finally:
            coord.close()

    def test_standby_refuses_subscribers(self, tmp_path):
        """Chained replication is not a thing: subscribing to a standby
        answers an error reply."""
        cluster = _Cluster(tmp_path, interval_ms=20.0)
        try:
            conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            conn.connect(cluster.s_sock)
            from api_ratelimit_tpu.backends.sidecar import OP_REPL_SUBSCRIBE

            conn.sendall(
                _HDR.pack(MAGIC, VERSION, OP_REPL_SUBSCRIBE, 0)
                + struct.pack("<IQ", 0, 0)
            )
            assert _recv_exact(conn, 1) == b"\x01"
            conn.close()
        finally:
            cluster.close()
