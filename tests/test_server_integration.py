"""End-to-end integration tests: the real Runner booted in-process, driven
over real gRPC (v3 + legacy v2), HTTP /json, the health checker, the debug
port, and hot reload — the reference's integration pattern
(test/integration/integration_test.go:251-274: NewRunner + go runner.Run(),
then drive over the wire).

Ports are ephemeral (0) so parallel test runs can't collide — the reference
burns distinct fixed ports per scenario for the same reason (:47-48).
"""

import json
import os
import time
import urllib.request
import urllib.error

import grpc
import pytest

from api_ratelimit_tpu.pb import rls_grpc, rls_v3, rls_v2, health_pb2
from api_ratelimit_tpu.runner import Runner
from api_ratelimit_tpu.settings import Settings
from api_ratelimit_tpu.stats.sinks import TestSink

BASIC_CONFIG = """\
domain: basic
descriptors:
  - key: key1
    rate_limit:
      unit: second
      requests_per_unit: 50
  - key: one_per_minute
    rate_limit:
      unit: minute
      requests_per_unit: 1
"""

ANOTHER_CONFIG = """\
domain: another
descriptors:
  - key: key2
    rate_limit:
      unit: minute
      requests_per_unit: 20
  - key: key3
    rate_limit:
      unit: hour
      requests_per_unit: 10
"""


def make_runtime(tmp_path, watch_root=True):
    """Reference layout: RUNTIME_ROOT/RUNTIME_SUBDIRECTORY/config/*.yaml
    (test/integration/runtime/current/ratelimit/config)."""
    config_dir = tmp_path / "current" / "ratelimit" / "config"
    config_dir.mkdir(parents=True)
    (config_dir / "basic.yaml").write_text(BASIC_CONFIG)
    (config_dir / "another.yaml").write_text(ANOTHER_CONFIG)
    return str(tmp_path / "current"), "ratelimit", config_dir


@pytest.fixture
def running_server(tmp_path):
    runtime_path, subdir, config_dir = make_runtime(tmp_path)
    settings = Settings(
        port=0,
        grpc_port=0,
        debug_port=0,
        use_statsd=False,
        runtime_path=runtime_path,
        runtime_subdirectory=subdir,
        backend_type="memory",
        local_cache_size_in_bytes=0,
        expiration_jitter_max_seconds=0,
        log_level="ERROR",
    )
    runner = Runner(settings, sink=TestSink())
    runner.run_background()
    assert runner.wait_ready(10.0)
    yield runner, config_dir
    runner.stop()


def v3_request(domain, pairs_list, hits_addend=0):
    req = rls_v3.RateLimitRequest(domain=domain, hits_addend=hits_addend)
    for pairs in pairs_list:
        d = req.descriptors.add()
        for k, v in pairs:
            d.entries.add(key=k, value=v)
    return req


def http_get(port, path):
    try:
        with urllib.request.urlopen(f"http://localhost:{port}{path}") as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_grpc_v3_over_limit_sequence(running_server):
    runner, _ = running_server
    with grpc.insecure_channel(f"localhost:{runner.server.grpc_port}") as ch:
        stub = rls_grpc.RateLimitServiceV3Stub(ch)
        # one_per_minute: first call OK, second OVER_LIMIT
        # (integration_test.go over-limit sequences, :334-355)
        r1 = stub.ShouldRateLimit(v3_request("basic", [[("one_per_minute", "foo")]]))
        assert r1.overall_code == rls_v3.RateLimitResponse.OK
        assert r1.statuses[0].current_limit.requests_per_unit == 1
        assert r1.statuses[0].current_limit.unit == rls_v3.RateLimitResponse.RateLimit.MINUTE
        assert r1.statuses[0].limit_remaining == 0
        r2 = stub.ShouldRateLimit(v3_request("basic", [[("one_per_minute", "foo")]]))
        assert r2.overall_code == rls_v3.RateLimitResponse.OVER_LIMIT
        assert r2.statuses[0].limit_remaining == 0

        # unmatched descriptor: OK with no current_limit
        r3 = stub.ShouldRateLimit(v3_request("basic", [[("unmatched", "x")]]))
        assert r3.overall_code == rls_v3.RateLimitResponse.OK
        assert not r3.statuses[0].HasField("current_limit")

        # multi-descriptor aggregation: one over -> overall OVER_LIMIT
        r4 = stub.ShouldRateLimit(
            v3_request("basic", [[("key1", "a")], [("one_per_minute", "foo")]])
        )
        assert r4.overall_code == rls_v3.RateLimitResponse.OVER_LIMIT
        assert r4.statuses[0].code == rls_v3.RateLimitResponse.OK
        assert r4.statuses[1].code == rls_v3.RateLimitResponse.OVER_LIMIT


def test_grpc_v3_stats_counters(running_server):
    runner, _ = running_server
    with grpc.insecure_channel(f"localhost:{runner.server.grpc_port}") as ch:
        stub = rls_grpc.RateLimitServiceV3Stub(ch)
        for _ in range(3):
            stub.ShouldRateLimit(v3_request("another", [[("key2", "dude")]]))
    snap = runner.stats_store.debug_snapshot()
    # exact reference stat paths (README.md:392-427); stats attach to the
    # configured rule's composite key (config_impl.go:64-71)
    assert snap["ratelimit.service.rate_limit.another.key2.total_hits"] == 3
    assert snap["ratelimit.service.rate_limit.another.key2.over_limit"] == 0
    assert snap["ratelimit.service.config_load_success"] >= 1


def test_grpc_v3_error_on_empty_domain(running_server):
    runner, _ = running_server
    with grpc.insecure_channel(f"localhost:{runner.server.grpc_port}") as ch:
        stub = rls_grpc.RateLimitServiceV3Stub(ch)
        with pytest.raises(grpc.RpcError) as err:
            stub.ShouldRateLimit(v3_request("", [[("key1", "a")]]))
        # request/config errors are INTERNAL (retrying cannot help);
        # backend failures map to UNAVAILABLE so Envoy can retry those
        assert err.value.code() == grpc.StatusCode.INTERNAL
        assert "domain" in err.value.details()
    snap = runner.stats_store.debug_snapshot()
    assert snap["ratelimit.service.call.should_rate_limit.service_error"] == 1


def test_grpc_v2_legacy(running_server):
    """Legacy v2 end-to-end (integration_test.go:491-601)."""
    runner, _ = running_server
    req = rls_v2.RateLimitRequest(domain="basic")
    d = req.descriptors.add()
    d.entries.add(key="one_per_minute", value="legacy")
    with grpc.insecure_channel(f"localhost:{runner.server.grpc_port}") as ch:
        stub = rls_grpc.RateLimitServiceV2Stub(ch)
        r1 = stub.ShouldRateLimit(req)
        assert r1.overall_code == rls_v2.RateLimitResponse.OK
        assert r1.statuses[0].current_limit.requests_per_unit == 1
        r2 = stub.ShouldRateLimit(req)
        assert r2.overall_code == rls_v2.RateLimitResponse.OVER_LIMIT


def test_http_json_status_mapping(running_server):
    """200/429/400 mapping (server_impl.go:62-104)."""
    runner, _ = running_server
    port = runner.server.http_port
    url = f"http://localhost:{port}/json"

    def post(body):
        req = urllib.request.Request(
            url, data=body.encode(), headers={"Content-Type": "application/json"}
        )
        try:
            with urllib.request.urlopen(req) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    body = json.dumps(
        {
            "domain": "basic",
            "descriptors": [{"entries": [{"key": "one_per_minute", "value": "json"}]}],
        }
    )
    status, text = post(body)
    assert status == 200
    assert json.loads(text)["overallCode"] == "OK"

    status, text = post(body)
    assert status == 429
    assert json.loads(text)["overallCode"] == "OVER_LIMIT"

    assert post("")[0] == 400
    assert post("{nonsense")[0] == 400


def test_http_json_malformed_content_length(running_server):
    """A garbage or negative Content-Length must map to 400, not a
    ValueError that drops the connection (or an unbounded read)."""
    import http.client

    runner, _ = running_server
    port = runner.server.http_port
    for bad in ("abc", "-5"):
        conn = http.client.HTTPConnection("localhost", port, timeout=5)
        try:
            conn.putrequest("POST", "/json")
            conn.putheader("Content-Type", "application/json")
            conn.putheader("Content-Length", bad)
            conn.endheaders()
            resp = conn.getresponse()
            assert resp.status == 400, (bad, resp.status)
            resp.read()
        finally:
            conn.close()


def test_healthcheck_and_grpc_health(running_server):
    runner, _ = running_server
    status, text = http_get(runner.server.http_port, "/healthcheck")
    assert (status, text) == (200, "OK")
    with grpc.insecure_channel(f"localhost:{runner.server.grpc_port}") as ch:
        check = ch.unary_unary(
            "/grpc.health.v1.Health/Check",
            request_serializer=health_pb2.HealthCheckRequest.SerializeToString,
            response_deserializer=health_pb2.HealthCheckResponse.FromString,
        )
        resp = check(health_pb2.HealthCheckRequest())
        assert resp.status == health_pb2.HealthCheckResponse.SERVING

    # flip to unhealthy (the SIGTERM drain path, health.go:28-35)
    runner.server.health.fail()
    status, _ = http_get(runner.server.http_port, "/healthcheck")
    assert status == 500
    with grpc.insecure_channel(f"localhost:{runner.server.grpc_port}") as ch:
        check = ch.unary_unary(
            "/grpc.health.v1.Health/Check",
            request_serializer=health_pb2.HealthCheckRequest.SerializeToString,
            response_deserializer=health_pb2.HealthCheckResponse.FromString,
        )
        resp = check(health_pb2.HealthCheckRequest())
        assert resp.status == health_pb2.HealthCheckResponse.NOT_SERVING


def test_grpc_health_watch_streams_transition(running_server):
    """The streaming Watch RPC (reference: the stock grpc-health server
    registered at health.go:21-27 serves Check AND Watch): the first message
    is the current status, and the SIGTERM-drain fail() pushes NOT_SERVING
    to the open stream without the client re-polling."""
    import threading

    runner, _ = running_server
    with grpc.insecure_channel(f"localhost:{runner.server.grpc_port}") as ch:
        watch = ch.unary_stream(
            "/grpc.health.v1.Health/Watch",
            request_serializer=health_pb2.HealthCheckRequest.SerializeToString,
            response_deserializer=health_pb2.HealthCheckResponse.FromString,
        )
        stream = watch(health_pb2.HealthCheckRequest(service="ratelimit"))
        first = next(stream)
        assert first.status == health_pb2.HealthCheckResponse.SERVING

        # flip AFTER the stream is established; the update must be pushed
        threading.Timer(0.1, runner.server.health.fail).start()
        second = next(stream)
        assert second.status == health_pb2.HealthCheckResponse.NOT_SERVING
        stream.cancel()

        # unknown service: Watch streams SERVICE_UNKNOWN (Check -> NOT_FOUND)
        stream2 = watch(health_pb2.HealthCheckRequest(service="nope"))
        assert next(stream2).status == health_pb2.HealthCheckResponse.SERVICE_UNKNOWN
        stream2.cancel()

        check = ch.unary_unary(
            "/grpc.health.v1.Health/Check",
            request_serializer=health_pb2.HealthCheckRequest.SerializeToString,
            response_deserializer=health_pb2.HealthCheckResponse.FromString,
        )
        with pytest.raises(grpc.RpcError) as err:
            check(health_pb2.HealthCheckRequest(service="nope"))
        assert err.value.code() == grpc.StatusCode.NOT_FOUND


def test_grpc_health_watch_cap(running_server):
    """Each sync Watch stream pins a gRPC worker thread; beyond MAX_WATCHERS
    the server answers RESOURCE_EXHAUSTED instead of letting health probes
    starve the ratelimit RPC pool."""
    from api_ratelimit_tpu.server.health import HealthChecker

    runner, _ = running_server
    with grpc.insecure_channel(f"localhost:{runner.server.grpc_port}") as ch:
        watch = ch.unary_stream(
            "/grpc.health.v1.Health/Watch",
            request_serializer=health_pb2.HealthCheckRequest.SerializeToString,
            response_deserializer=health_pb2.HealthCheckResponse.FromString,
        )
        streams = []
        try:
            for _ in range(HealthChecker.MAX_WATCHERS):
                s = watch(health_pb2.HealthCheckRequest())
                assert next(s).status == health_pb2.HealthCheckResponse.SERVING
                streams.append(s)
            overflow = watch(health_pb2.HealthCheckRequest())
            with pytest.raises(grpc.RpcError) as err:
                next(overflow)
            assert err.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
        finally:
            for s in streams:
                s.cancel()
        # slots free up once watchers disconnect
        deadline = time.time() + 5
        while time.time() < deadline:
            try:
                s = watch(health_pb2.HealthCheckRequest())
                assert next(s).status == health_pb2.HealthCheckResponse.SERVING
                s.cancel()
                break
            except grpc.RpcError:
                time.sleep(0.1)
        else:
            pytest.fail("watcher slot never freed after cancels")


def test_debug_endpoints(running_server):
    runner, _ = running_server
    port = runner.server.debug_port

    status, text = http_get(port, "/")
    assert status == 200
    assert "/stats" in text and "/rlconfig" in text

    status, text = http_get(port, "/stats")
    assert status == 200
    assert "config_load_success" in text

    status, text = http_get(port, "/rlconfig")
    assert status == 200
    assert "basic" in text and "one_per_minute" in text

    status, text = http_get(port, "/debug/pprof/")
    assert status == 200
    assert "thread" in text

    # CPU profile: short sample window; collapsed-stack lines "a;b;c N".
    # Other live threads (grpc workers, watchers) are guaranteed samples.
    status, text = http_get(port, "/debug/pprof/profile?seconds=0.3&hz=200")
    assert status == 200
    lines = [line for line in text.splitlines() if line.strip()]
    assert lines, "profiler sampled no stacks"
    frames, count = lines[0].rsplit(" ", 1)
    assert int(count) >= 1
    assert ":" in frames  # file:line:func frames

    # heap: a bare GET is side-effect-free (scrapers must not arm
    # tracemalloc); ?start=1 arms, a later GET returns the snapshot
    import tracemalloc

    if tracemalloc.is_tracing():  # PYTHONTRACEMALLOC pre-arms it
        tracemalloc.stop()
    status, text = http_get(port, "/debug/pprof/heap")
    assert status == 200 and "not armed" in text
    assert not tracemalloc.is_tracing()
    status, text = http_get(port, "/debug/pprof/heap?start=1")
    assert status == 200 and "armed" in text
    status, text = http_get(port, "/debug/pprof/heap?top=5")
    assert status == 200
    snap = json.loads(text)
    assert snap["traced_current_bytes"] >= 0
    assert isinstance(snap["top"], list)

    # bad params -> 400, not a dropped connection
    assert http_get(port, "/debug/pprof/profile?seconds=abc")[0] == 400
    assert http_get(port, "/debug/pprof/heap?top=x")[0] == 400

    # disarm tracemalloc (it must not stay on for the process lifetime)
    status, text = http_get(port, "/debug/pprof/heap?stop=1")
    assert status == 200 and "stopped" in text
    import tracemalloc

    assert not tracemalloc.is_tracing()

    assert http_get(port, "/nope")[0] == 404


def test_hot_reload(running_server):
    """Copy a new config into the watched dir; poll config_load_success and
    verify the new domain works (integration_test.go:603-708)."""
    runner, config_dir = running_server
    before = runner.stats_store.debug_snapshot()[
        "ratelimit.service.config_load_success"
    ]
    (config_dir / "reload.yaml").write_text(
        "domain: reload\n"
        "descriptors:\n"
        "  - key: block\n"
        "    rate_limit:\n"
        "      unit: second\n"
        "      requests_per_unit: 0\n"
    )
    deadline = time.time() + 10.0
    while time.time() < deadline:
        snap = runner.stats_store.debug_snapshot()
        if snap["ratelimit.service.config_load_success"] > before:
            break
        time.sleep(0.05)
    else:
        pytest.fail("config reload never observed")

    with grpc.insecure_channel(f"localhost:{runner.server.grpc_port}") as ch:
        stub = rls_grpc.RateLimitServiceV3Stub(ch)
        resp = stub.ShouldRateLimit(v3_request("reload", [[("block", "x")]]))
        # requests_per_unit: 0 -> always over limit
        assert resp.overall_code == rls_v3.RateLimitResponse.OVER_LIMIT


def test_config_error_keeps_old_config(running_server):
    """A bad reload bumps config_load_error and keeps serving the old rules
    (ratelimit.go:81-92)."""
    runner, config_dir = running_server
    (config_dir / "broken.yaml").write_text("domain: basic\n")  # duplicate domain
    deadline = time.time() + 10.0
    while time.time() < deadline:
        snap = runner.stats_store.debug_snapshot()
        if snap.get("ratelimit.service.config_load_error", 0) >= 1:
            break
        time.sleep(0.05)
    else:
        pytest.fail("config load error never observed")

    with grpc.insecure_channel(f"localhost:{runner.server.grpc_port}") as ch:
        stub = rls_grpc.RateLimitServiceV3Stub(ch)
        resp = stub.ShouldRateLimit(v3_request("basic", [[("key1", "still")]]))
        assert resp.overall_code == rls_v3.RateLimitResponse.OK
        assert resp.statuses[0].current_limit.requests_per_unit == 50


class TestBackendMatrix:
    """BACKEND_TYPE matrix through the full runner, reference-style
    (integration_test.go:49-92 runs {redis, redis+persecond, memcache}
    scenarios; here the live backends are the in-process fakes)."""

    def _boot(self, tmp_path, **settings_kw):
        runtime_path, subdir, config_dir = make_runtime(tmp_path)
        settings = Settings(
            port=0,
            grpc_port=0,
            debug_port=0,
            use_statsd=False,
            runtime_path=runtime_path,
            runtime_subdirectory=subdir,
            expiration_jitter_max_seconds=0,
            log_level="ERROR",
            **settings_kw,
        )
        runner = Runner(settings, sink=TestSink())
        runner.run_background()
        assert runner.wait_ready(10.0)
        return runner

    def _over_limit_sequence(self, runner):
        with grpc.insecure_channel(f"localhost:{runner.server.grpc_port}") as ch:
            stub = rls_grpc.RateLimitServiceV3Stub(ch)
            req = v3_request("basic", [[("one_per_minute", "matrix")]])
            codes = [stub.ShouldRateLimit(req).overall_code for _ in range(3)]
        return codes

    def test_redis_backend(self, tmp_path):
        from api_ratelimit_tpu.testing.fake_redis import FakeRedisServer

        server = FakeRedisServer()
        try:
            runner = self._boot(
                tmp_path,
                backend_type="redis",
                redis_socket_type="tcp",
                redis_url=server.addr,
            )
            OK = rls_v3.RateLimitResponse.OK
            OVER = rls_v3.RateLimitResponse.OVER_LIMIT
            assert self._over_limit_sequence(runner) == [OK, OVER, OVER]
            assert any(c[0] == b"INCRBY" for c in server.commands_seen)
            runner.stop()
        finally:
            server.close()

    def test_redis_backend_with_per_second_pool(self, tmp_path):
        from api_ratelimit_tpu.testing.fake_redis import FakeRedisServer

        main = FakeRedisServer()
        second = FakeRedisServer()
        try:
            runner = self._boot(
                tmp_path,
                backend_type="redis",
                redis_socket_type="tcp",
                redis_url=main.addr,
                redis_per_second=True,
                redis_per_second_socket_type="tcp",
                redis_per_second_url=second.addr,
            )
            with grpc.insecure_channel(f"localhost:{runner.server.grpc_port}") as ch:
                stub = rls_grpc.RateLimitServiceV3Stub(ch)
                # key1 is unit=second -> per-second pool; one_per_minute -> main
                stub.ShouldRateLimit(v3_request("basic", [[("key1", "a")]]))
                stub.ShouldRateLimit(
                    v3_request("basic", [[("one_per_minute", "b")]])
                )
            second_keys = [
                c[1] for c in second.commands_seen if c[0] == b"INCRBY"
            ]
            main_keys = [c[1] for c in main.commands_seen if c[0] == b"INCRBY"]
            assert any(b"key1" in k for k in second_keys)
            assert any(b"one_per_minute" in k for k in main_keys)
            assert not any(b"one_per_minute" in k for k in second_keys)
            runner.stop()
        finally:
            main.close()
            second.close()

    def test_memcache_backend(self, tmp_path):
        from api_ratelimit_tpu.testing.fake_memcache import FakeMemcacheServer

        server = FakeMemcacheServer()
        try:
            runner = self._boot(
                tmp_path,
                backend_type="memcache",
                memcache_host_port=server.addr,
            )
            with grpc.insecure_channel(f"localhost:{runner.server.grpc_port}") as ch:
                stub = rls_grpc.RateLimitServiceV3Stub(ch)
                req = v3_request("basic", [[("one_per_minute", "mc")]])
                r1 = stub.ShouldRateLimit(req)
                assert r1.overall_code == rls_v3.RateLimitResponse.OK
                runner.service._cache.flush()  # join async increments
                r2 = stub.ShouldRateLimit(req)
                assert r2.overall_code == rls_v3.RateLimitResponse.OVER_LIMIT
            runner.stop()
        finally:
            server.close()

    def test_redis_down_surfaces_grpc_error_and_counter(self, tmp_path):
        from api_ratelimit_tpu.testing.fake_redis import FakeRedisServer

        server = FakeRedisServer()
        runner = self._boot(
            tmp_path,
            backend_type="redis",
            redis_socket_type="tcp",
            redis_url=server.addr,
        )
        server.close()
        with grpc.insecure_channel(f"localhost:{runner.server.grpc_port}") as ch:
            stub = rls_grpc.RateLimitServiceV3Stub(ch)
            with pytest.raises(grpc.RpcError) as err:
                stub.ShouldRateLimit(v3_request("basic", [[("key1", "a")]]))
            # backend failure: UNAVAILABLE, the Envoy-retriable class
            assert err.value.code() == grpc.StatusCode.UNAVAILABLE
        snap = runner.stats_store.debug_snapshot()
        assert snap["ratelimit.service.call.should_rate_limit.redis_error"] == 1
        runner.stop()


class TestBackendMatrixTopologies:
    """The reference's TLS/auth/sentinel scenarios (integration_test.go:49-92
    drives stunnel TLS, AUTH, and sentinel-monitored pairs; here the live
    fakes provide the same wire behaviors)."""

    _boot = TestBackendMatrix._boot
    _over_limit_sequence = TestBackendMatrix._over_limit_sequence

    def test_redis_tls_with_auth(self, tmp_path):
        from api_ratelimit_tpu.testing.fake_redis import FakeRedisServer

        server = FakeRedisServer(password="hunter2", tls=True)
        try:
            runner = self._boot(
                tmp_path,
                backend_type="redis",
                redis_socket_type="tcp",
                redis_url=server.addr,
                redis_auth="hunter2",
                redis_tls=True,
            )
            codes = self._over_limit_sequence(runner)
            assert codes == [
                rls_v3.RateLimitResponse.OK,
                rls_v3.RateLimitResponse.OVER_LIMIT,
                rls_v3.RateLimitResponse.OVER_LIMIT,
            ]
            runner.stop()
        finally:
            server.close()

    def test_redis_cluster_topology(self, tmp_path):
        from api_ratelimit_tpu.testing.fake_redis import FakeRedisServer

        node = FakeRedisServer()  # advertises itself for all 16384 slots
        try:
            runner = self._boot(
                tmp_path,
                backend_type="redis",
                redis_socket_type="tcp",
                redis_type="CLUSTER",
                redis_url=node.addr,
            )
            codes = self._over_limit_sequence(runner)
            assert codes == [
                rls_v3.RateLimitResponse.OK,
                rls_v3.RateLimitResponse.OVER_LIMIT,
                rls_v3.RateLimitResponse.OVER_LIMIT,
            ]
            assert node.get_int_prefix("basic_one_per_minute_matrix") == 3
            runner.stop()
        finally:
            node.close()

    def test_redis_sentinel_topology(self, tmp_path):
        from api_ratelimit_tpu.testing.fake_redis import FakeRedisServer

        master = FakeRedisServer()
        sentinel = FakeRedisServer(
            sentinel_master=("mymaster", "127.0.0.1", master.port)
        )
        try:
            runner = self._boot(
                tmp_path,
                backend_type="redis",
                redis_socket_type="tcp",
                redis_type="SENTINEL",
                redis_url=f"mymaster,{sentinel.addr}",
            )
            codes = self._over_limit_sequence(runner)
            assert codes == [
                rls_v3.RateLimitResponse.OK,
                rls_v3.RateLimitResponse.OVER_LIMIT,
                rls_v3.RateLimitResponse.OVER_LIMIT,
            ]
            # counters landed on the resolved master, not the sentinel
            assert master.get_int_prefix("basic_one_per_minute_matrix") == 3
            runner.stop()
        finally:
            sentinel.close()
            master.close()


PROM_SAMPLE = __import__("re").compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9]+(\.[0-9]+)?([eE][+-][0-9]+)?$"
)
PROM_COMMENT = __import__("re").compile(
    r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (counter|gauge|histogram|summary)$"
)


def test_metrics_endpoint_full_pipeline(tmp_path):
    """The acceptance scrape: boot the REAL tpu backend, drive traffic, and
    parse every line of GET /metrics — it must carry the whole per-stage
    pipeline: total request latency histogram, batcher queue-wait +
    batch-size histograms, device launch/readback histograms, slab
    occupancy/eviction gauges, and the batcher queue-depth gauge."""
    runtime_path, subdir, _ = make_runtime(tmp_path)
    settings = Settings(
        port=0,
        grpc_port=0,
        debug_port=0,
        use_statsd=False,
        runtime_path=runtime_path,
        runtime_subdirectory=subdir,
        backend_type="tpu",
        tpu_slab_slots=1 << 12,
        tpu_batch_window=0.0002,  # dispatcher mode: queue-wait is real
        expiration_jitter_max_seconds=0,
        log_level="ERROR",
    )
    runner = Runner(settings, sink=TestSink())
    runner.run_background()
    assert runner.wait_ready(10.0)
    try:
        with grpc.insecure_channel(f"localhost:{runner.server.grpc_port}") as ch:
            stub = rls_grpc.RateLimitServiceV3Stub(ch)
            for i in range(8):
                stub.ShouldRateLimit(
                    v3_request("basic", [[("key1", f"k{i}")]])
                )
        status, text = http_get(runner.server.debug_port, "/metrics")
        assert status == 200

        lines = text.strip().splitlines()
        assert lines
        for line in lines:  # every line parses as exposition format
            assert PROM_SAMPLE.match(line) or PROM_COMMENT.match(line), line

        required = [
            # total request latency (histogram) + transport receive stage
            "ratelimit_service_call_should_rate_limit_latency_ms_bucket",
            "ratelimit_service_call_should_rate_limit_latency_ms_count",
            "ratelimit_service_transport_grpc_ms_bucket",
            # batcher: queue-wait histogram, batch-size distribution, depth
            "ratelimit_batcher_queue_wait_ms_bucket",
            "ratelimit_batcher_batch_size_bucket",
            "ratelimit_batcher_queue_depth",
            "ratelimit_batcher_inflight",
            # device stages
            "ratelimit_device_pack_ms_bucket",
            "ratelimit_device_launch_ms_bucket",
            "ratelimit_device_readback_ms_bucket",
            # slab health gauges (eviction mix + contention drops; occupancy)
            "ratelimit_slab_evictions_expired",
            "ratelimit_slab_evictions_window",
            "ratelimit_slab_evictions_live",
            "ratelimit_slab_drops",
            "ratelimit_slab_occupancy",
            "ratelimit_slab_live_slots",
        ]
        for name in required:
            assert any(l.startswith(name) for l in lines), f"missing {name}"

        # the request latency histogram actually observed the traffic
        count_line = next(
            l
            for l in lines
            if l.startswith(
                "ratelimit_service_call_should_rate_limit_latency_ms_count"
            )
        )
        assert int(count_line.rsplit(" ", 1)[1]) >= 8
        # histograms are cumulative: the +Inf bucket equals the count
        inf_line = next(
            l
            for l in lines
            if l.startswith(
                "ratelimit_service_call_should_rate_limit_latency_ms_bucket"
            )
            and 'le="+Inf"' in l
        )
        assert inf_line.rsplit(" ", 1)[1] == count_line.rsplit(" ", 1)[1]
    finally:
        runner.stop()


def test_metrics_endpoint_can_be_disabled(tmp_path):
    runtime_path, subdir, _ = make_runtime(tmp_path)
    settings = Settings(
        port=0,
        grpc_port=0,
        debug_port=0,
        use_statsd=False,
        runtime_path=runtime_path,
        runtime_subdirectory=subdir,
        backend_type="memory",
        debug_metrics_enabled=False,
        expiration_jitter_max_seconds=0,
        log_level="ERROR",
    )
    runner = Runner(settings, sink=TestSink())
    runner.run_background()
    assert runner.wait_ready(10.0)
    try:
        assert http_get(runner.server.debug_port, "/metrics")[0] == 404
        assert http_get(runner.server.debug_port, "/stats")[0] == 200
    finally:
        runner.stop()


def test_slow_request_exemplar_links_to_forced_span(tmp_path, monkeypatch):
    """The tail-capture acceptance path: a slow request (forced via the
    service's debug_inject_latency_s test hook) lands in the top latency
    bucket, attaches its trace id as the histogram exemplar, and
    force-samples its span into /debug/traces EVEN THOUGH the client sent
    x-b3-sampled: 0 — one click from p99 outlier to per-stage spans."""
    from api_ratelimit_tpu import tracing

    monkeypatch.setenv("K_TRACING_ENABLED", "true")
    runtime_path, subdir, _ = make_runtime(tmp_path)
    settings = Settings(
        port=0,
        grpc_port=0,
        debug_port=0,
        use_statsd=False,
        runtime_path=runtime_path,
        runtime_subdirectory=subdir,
        backend_type="memory",
        metrics_latency_buckets_ms="0.5,1,5,250",  # top bucket: >250ms
        expiration_jitter_max_seconds=0,
        log_level="ERROR",
    )
    runner = Runner(settings, sink=TestSink())
    runner.run_background()
    assert runner.wait_ready(10.0)
    try:
        trace_id = "feedfacefeedfacefeedfacefeedface"
        b3_unsampled = (
            ("x-b3-traceid", trace_id),
            ("x-b3-spanid", "00000000000000cd"),
            ("x-b3-sampled", "0"),
        )
        with grpc.insecure_channel(f"localhost:{runner.server.grpc_port}") as ch:
            stub = rls_grpc.RateLimitServiceV3Stub(ch)
            # fast + unsampled: honored — no span recorded, no exemplar
            stub.ShouldRateLimit(
                v3_request("basic", [[("key1", "fast")]]), metadata=b3_unsampled
            )
            spans = runner.tracer.finished_spans()
            assert not any(s.context.trace_id == int(trace_id, 16) for s in spans)
            snap = runner.stats_store.debug_snapshot()
            key = "ratelimit.service.call.should_rate_limit.latency_ms"
            assert snap[f"{key}.count"] >= 1
            assert f"{key}.exemplar" not in snap

            # force the slow path: > the 250ms top boundary
            runner.service.debug_inject_latency_s = 0.3
            stub.ShouldRateLimit(
                v3_request("basic", [[("key1", "slow")]]), metadata=b3_unsampled
            )

        snap = runner.stats_store.debug_snapshot()
        assert snap[f"{key}.exemplar"] == trace_id

        # the matching span was force-sampled into /debug/traces
        status, body = http_get(runner.server.debug_port, "/debug/traces")
        assert status == 200
        dump = json.loads(body)
        forced = [s for s in dump["spans"] if s["trace_id"] == trace_id]
        assert forced, "force-sampled span missing from /debug/traces"
        assert any(s["tags"].get("sampling.forced") for s in forced)
    finally:
        runner.stop()
        tracing.reset_global_tracer()


def test_duration_until_reset_decays(running_server):
    """DurationUntilReset shrinks as the window ages
    (integration_test.go:476-487 asserts decay across a 2s sleep)."""
    runner, _ = running_server
    with grpc.insecure_channel(f"localhost:{runner.server.grpc_port}") as ch:
        stub = rls_grpc.RateLimitServiceV3Stub(ch)
        # a minute-window rollover between the paired calls resets the
        # duration upward; retry once so only a double rollover (~0.03%)
        # could flake, while a non-decaying implementation still fails
        for attempt in ("decay-a", "decay-b"):
            req = v3_request("basic", [[("one_per_minute", attempt)]])
            d1 = stub.ShouldRateLimit(req).statuses[0].duration_until_reset.seconds
            time.sleep(1.1)
            d2 = stub.ShouldRateLimit(req).statuses[0].duration_until_reset.seconds
            assert 0 < d1 <= 60
            if d2 < d1:
                return
    assert d2 < d1


def test_tracing_end_to_end(tmp_path, monkeypatch):
    """B3 context from gRPC metadata -> server span in the recording tracer,
    exposed on /debug/traces (runner.go:90-95 + interceptor wiring)."""
    from api_ratelimit_tpu import tracing

    monkeypatch.setenv("K_TRACING_ENABLED", "true")
    runtime_path, subdir, _ = make_runtime(tmp_path)
    settings = Settings(
        port=0,
        grpc_port=0,
        debug_port=0,
        use_statsd=False,
        runtime_path=runtime_path,
        runtime_subdirectory=subdir,
        backend_type="memory",
        expiration_jitter_max_seconds=0,
        log_level="ERROR",
    )
    runner = Runner(settings, sink=TestSink())
    runner.run_background()
    assert runner.wait_ready(10.0)
    try:
        assert isinstance(runner.tracer, tracing.RecordingTracer)
        trace_id = "0123456789abcdef0123456789abcdef"
        with grpc.insecure_channel(f"localhost:{runner.server.grpc_port}") as ch:
            stub = rls_grpc.RateLimitServiceV3Stub(ch)
            stub.ShouldRateLimit(
                v3_request("basic", [[("key1", "a")]]),
                metadata=(
                    ("x-b3-traceid", trace_id),
                    ("x-b3-spanid", "00000000000000ab"),
                ),
            )
        spans = runner.tracer.finished_spans()
        rpc = [s for s in spans if "ShouldRateLimit" in s.operation_name]
        assert rpc, f"no RPC span among {[s.operation_name for s in spans]}"
        got = rpc[-1]
        assert f"{got.context.trace_id:032x}" == trace_id
        assert got.parent_id == 0xAB
        assert got.tags.get("backend") == "memory"
        events = [f.get("event") for _, f in got.logs]
        assert "shouldRateLimitWorker.start" in events

        status, body = http_get(runner.server.debug_port, "/debug/traces")
        assert status == 200
        dump = json.loads(body)
        assert any(
            "ShouldRateLimit" in s["operation_name"] for s in dump["spans"]
        )
    finally:
        runner.stop()
        tracing.reset_global_tracer()
