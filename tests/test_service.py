"""Service layer tests — the Python twin of test/service/ratelimit_test.go:
OK/OVER_LIMIT aggregation, reload success/failure keeping old config, empty
domain/descriptor errors, cache error counting, sleep-on-throttle semantics,
detail headers."""

import base64
import json

import pytest

from api_ratelimit_tpu.config.loader import ConfigFile, load_config
from api_ratelimit_tpu.limiter.cache import CacheError
from api_ratelimit_tpu.models import Code, Descriptor, RateLimitRequest
from api_ratelimit_tpu.models.response import (
    DescriptorStatus,
    DoLimitResponse,
    RateLimitValue,
)
from api_ratelimit_tpu.service import RateLimitService, ServiceError
from api_ratelimit_tpu.stats import Store, TestSink
from api_ratelimit_tpu.utils import FakeTimeSource


class FakeSnapshot:
    def __init__(self, files: dict[str, str]):
        self._files = files

    def keys(self):
        return list(self._files)

    def get(self, key):
        return self._files[key]


class FakeRuntime:
    def __init__(self, files: dict[str, str]):
        self.files = dict(files)
        self.callbacks = []

    def snapshot(self):
        return FakeSnapshot(self.files)

    def add_update_callback(self, cb):
        self.callbacks.append(cb)

    def update(self, files: dict[str, str]):
        self.files = dict(files)
        for cb in self.callbacks:
            cb()


class FakeCache:
    """Scripted RateLimitCache."""

    def __init__(self):
        self.next_statuses = []
        self.next_throttle = 0
        self.calls = []
        self.raise_error = None

    def do_limit(self, request, limits):
        self.calls.append((request, list(limits)))
        if self.raise_error is not None:
            raise self.raise_error
        statuses = self.next_statuses or [
            DescriptorStatus(code=Code.OK) for _ in request.descriptors
        ]
        return DoLimitResponse(
            descriptor_statuses=list(statuses), throttle_millis=self.next_throttle
        )

    def flush(self):
        pass


BASIC_YAML = """
domain: test-domain
descriptors:
  - key: k
    value: v
    rate_limit: {unit: minute, requests_per_unit: 10}
"""

OTHER_YAML = """
domain: other-domain
descriptors:
  - key: k2
    rate_limit: {unit: hour, requests_per_unit: 5}
"""

BAD_YAML = "domain: [this is not\nvalid yaml"


def req(*pairs, domain="test-domain"):
    return RateLimitRequest(
        domain=domain,
        descriptors=tuple(Descriptor.of(p) for p in pairs),
        hits_addend=1,
    )


def make_service(files=None, cache=None, watch_root=True, **kw):
    runtime = FakeRuntime(
        files if files is not None else {"config.basic": BASIC_YAML}
    )
    cache = cache or FakeCache()
    sink = TestSink()
    store = Store(sink)
    svc = RateLimitService(
        runtime=runtime,
        cache=cache,
        stats_scope=store.scope("ratelimit"),
        time_source=FakeTimeSource(1_000_000),
        runtime_watch_root=watch_root,
        **kw,
    )
    return svc, runtime, cache, store, sink


class TestServiceBasics:
    def test_initial_load_and_ok(self):
        svc, _, cache, store, sink = make_service()
        overall, statuses, headers = svc.should_rate_limit(req(("k", "v")))
        assert overall == Code.OK
        assert len(statuses) == 1
        assert headers == []
        # the resolved limit was passed to the cache
        _, limits = cache.calls[0]
        assert limits[0].requests_per_unit == 10
        store.flush()
        assert sink.counters["ratelimit.config_load_success"] == 1

    def test_unmatched_descriptor_gets_none_limit(self):
        svc, _, cache, _, _ = make_service()
        svc.should_rate_limit(req(("nope", "x")))
        _, limits = cache.calls[0]
        assert limits == [None]

    def test_overall_code_aggregation(self):
        svc, _, cache, _, _ = make_service()
        cache.next_statuses = [
            DescriptorStatus(code=Code.OK),
            DescriptorStatus(code=Code.OVER_LIMIT),
        ]
        overall, statuses, _ = svc.should_rate_limit(req(("k", "v"), ("k", "v")))
        assert overall == Code.OVER_LIMIT
        assert [s.code for s in statuses] == [Code.OK, Code.OVER_LIMIT]

    def test_empty_domain_raises_service_error(self):
        svc, _, _, store, sink = make_service()
        with pytest.raises(ServiceError, match="domain must not be empty"):
            svc.should_rate_limit(req(("k", "v"), domain=""))
        store.flush()
        assert (
            sink.counters["ratelimit.call.should_rate_limit.service_error"] == 1
        )

    def test_empty_descriptors_raises_service_error(self):
        svc, _, _, _, _ = make_service()
        with pytest.raises(ServiceError, match="descriptor list must not be empty"):
            svc.should_rate_limit(RateLimitRequest(domain="test-domain"))

    def test_cache_error_counted_and_reraised(self):
        svc, _, cache, store, sink = make_service()
        cache.raise_error = CacheError("backend down")
        with pytest.raises(CacheError):
            svc.should_rate_limit(req(("k", "v")))
        store.flush()
        assert sink.counters["ratelimit.call.should_rate_limit.redis_error"] == 1

    def test_unexpected_exception_counted_and_typed(self):
        """The reference's recovery catches ANY panic, counts serviceError,
        and surfaces a typed error (ratelimit.go:260-290) — a bug-class
        exception must not bypass the alerting counters."""
        svc, _, cache, store, sink = make_service()
        cache.raise_error = RuntimeError("bug class")
        with pytest.raises(ServiceError, match="unexpected error"):
            svc.should_rate_limit(req(("k", "v")))
        store.flush()
        assert (
            sink.counters["ratelimit.call.should_rate_limit.service_error"] == 1
        )


class TestConfigReload:
    def test_reload_picks_up_new_domain(self):
        svc, runtime, _, store, sink = make_service()
        assert svc.get_current_config().get_limit(
            "other-domain", Descriptor.of(("k2", "x"))
        ) is None
        runtime.update(
            {"config.basic": BASIC_YAML, "config.other": OTHER_YAML}
        )
        limit = svc.get_current_config().get_limit(
            "other-domain", Descriptor.of(("k2", "x"))
        )
        assert limit is not None and limit.requests_per_unit == 5
        store.flush()
        assert sink.counters["ratelimit.config_load_success"] == 2

    def test_bad_reload_keeps_old_config(self):
        svc, runtime, _, store, sink = make_service()
        runtime.update({"config.basic": BAD_YAML})
        # old config still answers
        limit = svc.get_current_config().get_limit(
            "test-domain", Descriptor.of(("k", "v"))
        )
        assert limit is not None and limit.requests_per_unit == 10
        store.flush()
        assert sink.counters["ratelimit.config_load_error"] == 1
        assert sink.counters["ratelimit.config_load_success"] == 1

    def test_initial_load_failure_leaves_no_config(self):
        svc, _, _, store, sink = make_service(files={"config.bad": BAD_YAML})
        with pytest.raises(ServiceError, match="no rate limit configuration"):
            svc.should_rate_limit(req(("k", "v")))
        store.flush()
        assert sink.counters["ratelimit.config_load_error"] == 1

    def test_watch_root_filters_non_config_keys(self):
        svc, _, _, _, _ = make_service(
            files={"config.basic": BASIC_YAML, "ignored.key": BAD_YAML}
        )
        assert svc.get_current_config() is not None

    def test_watch_root_false_loads_all_keys(self):
        svc, _, _, _, _ = make_service(
            files={"anything": BASIC_YAML}, watch_root=False
        )
        limit = svc.get_current_config().get_limit(
            "test-domain", Descriptor.of(("k", "v"))
        )
        assert limit is not None


SLEEPY_YAML = """
domain: sleepy
descriptors:
  - key: k
    value: v
    rate_limit: {unit: minute, requests_per_unit: 10}
    sleep_on_throttle: true
    report_details: true
"""


class TestThrottleAndDetails:
    def test_sleep_on_throttle_sleeps_and_clears(self):
        svc, _, cache, _, _ = make_service(
            files={"config.sleepy": SLEEPY_YAML}, max_sleeping_routines=2
        )
        cache.next_throttle = 1500
        ts = svc._time_source
        _, _, headers = svc.should_rate_limit(req(("k", "v"), domain="sleepy"))
        assert ts.sleeps == [1.5]
        # server slept; throttle header must NOT be added (millis reset)
        assert all(h.key != "x-ratelimit-throttle-ms" for h in headers)

    def test_no_semaphore_no_sleep(self):
        svc, _, cache, _, _ = make_service(files={"config.sleepy": SLEEPY_YAML})
        cache.next_throttle = 1500
        ts = svc._time_source
        _, _, headers = svc.should_rate_limit(req(("k", "v"), domain="sleepy"))
        assert ts.sleeps == []
        # not slept server-side: throttle-ms header reported instead
        assert any(
            h.key == "x-ratelimit-throttle-ms" and h.value == "1500"
            for h in headers
        )

    def test_detail_header_is_base64_json(self):
        svc, _, cache, _, _ = make_service(files={"config.sleepy": SLEEPY_YAML})
        cache.next_statuses = [
            DescriptorStatus(
                code=Code.OVER_LIMIT,
                current_limit=RateLimitValue(10, unit=1),
                limit_remaining=0,
            )
        ]
        _, _, headers = svc.should_rate_limit(req(("k", "v"), domain="sleepy"))
        detail = next(h for h in headers if h.key == "x-ratelimit-details")
        pad = "=" * (-len(detail.value) % 4)
        decoded = json.loads(base64.urlsafe_b64decode(detail.value + pad))
        assert decoded["descriptor_statuses"][0]["code"] == "OVER_LIMIT"

    def test_no_details_for_plain_rules(self):
        svc, _, cache, _, _ = make_service()
        cache.next_throttle = 999
        _, _, headers = svc.should_rate_limit(req(("k", "v")))
        assert headers == []


class TestLoaderDirect:
    def test_load_config_duplicate_domain_raises(self):
        from api_ratelimit_tpu.models.config import ConfigError

        files = [
            ConfigFile("a.yaml", BASIC_YAML),
            ConfigFile("b.yaml", BASIC_YAML),
        ]
        store = Store(TestSink())
        with pytest.raises(ConfigError):
            load_config(files, store)
