"""Settings layer: env parsing with the reference's variable names
(src/settings/settings.go:10-48)."""

import pytest

from api_ratelimit_tpu.settings import Settings, new_settings


class TestSettings:
    def test_defaults(self):
        s = new_settings({})
        assert s.port == 8080
        assert s.grpc_port == 8081
        assert s.debug_port == 6070
        assert s.use_statsd is True
        assert s.runtime_path == "/srv/runtime_data/current"
        assert s.near_limit_ratio == pytest.approx(0.8)
        assert s.expiration_jitter_max_seconds == 300
        assert s.local_cache_size_in_bytes == 0
        assert s.backend_type == "tpu"

    def test_reference_env_names(self):
        # a nomad-style env block (nomad/apigw-ratelimit/common.hcl)
        s = new_settings(
            {
                "GRPC_PORT": "9484",
                "PORT": "9486",
                "DEBUG_PORT": "9485",
                "USE_STATSD": "false",
                "RUNTIME_ROOT": "/data/runtime",
                "RUNTIME_SUBDIRECTORY": "ratelimit",
                "RUNTIME_WATCH_ROOT": "false",
                "LOG_LEVEL": "debug",
                "MAX_SLEEPING_ROUTINES": "64",
                "LOCAL_CACHE_SIZE_IN_BYTES": "1000000",
                "NEAR_LIMIT_RATIO": "0.9",
                "EXPIRATION_JITTER_MAX_SECONDS": "0",
            }
        )
        assert s.grpc_port == 9484
        assert s.use_statsd is False
        assert s.runtime_subdirectory == "ratelimit"
        assert s.runtime_watch_root is False
        assert s.max_sleeping_routines == 64
        assert s.local_cache_size_in_bytes == 1_000_000
        assert s.near_limit_ratio == pytest.approx(0.9)
        assert s.expiration_jitter_max_seconds == 0

    def test_go_duration_strings(self):
        s = new_settings(
            {
                "REDIS_PIPELINE_WINDOW": "75us",
                "TPU_BATCH_WINDOW": "500us",
            }
        )
        assert s.redis_pipeline_window == pytest.approx(75e-6)
        assert s.tpu_batch_window == pytest.approx(500e-6)
        assert new_settings({"TPU_BATCH_WINDOW": "2ms"}).tpu_batch_window == (
            pytest.approx(2e-3)
        )

    def test_bad_value_raises(self):
        with pytest.raises(ValueError, match="GRPC_PORT"):
            new_settings({"GRPC_PORT": "not-a-port"})
        with pytest.raises(ValueError, match="USE_STATSD"):
            new_settings({"USE_STATSD": "maybe"})

    def test_empty_string_keeps_default(self):
        s = new_settings({"STATSD_HOST": ""})
        assert s.statsd_host == "localhost"

    def test_tpu_knobs(self):
        s = new_settings(
            {
                "BACKEND_TYPE": "tpu",
                "TPU_SLAB_SLOTS": "8388608",
                "TPU_BATCH_LIMIT": "32768",
                "TPU_MESH_DEVICES": "4",
                "TPU_USE_PALLAS": "false",
            }
        )
        assert s.tpu_slab_slots == 1 << 23
        assert s.tpu_batch_limit == 32768
        assert s.tpu_mesh_devices == 4
        assert s.tpu_use_pallas is False

    def test_dataclass_is_plain(self):
        assert Settings().port == 8080

    def test_metrics_knobs(self):
        s = new_settings(
            {
                "DEBUG_METRICS_ENABLED": "false",
                "METRICS_LATENCY_BUCKETS_MS": "5, 0.5,1,100",
            }
        )
        assert s.debug_metrics_enabled is False
        assert s.latency_buckets() == (0.5, 1.0, 5.0, 100.0)  # sorted
        # default: endpoint on, store-default ladder
        assert Settings().debug_metrics_enabled is True
        assert Settings().latency_buckets() is None

    def test_metrics_buckets_junk_raises(self):
        with pytest.raises(ValueError):
            new_settings(
                {"METRICS_LATENCY_BUCKETS_MS": "1,abc"}
            ).latency_buckets()
        with pytest.raises(ValueError):
            new_settings(
                {"METRICS_LATENCY_BUCKETS_MS": "-1,5"}
            ).latency_buckets()
