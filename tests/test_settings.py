"""Settings layer: env parsing with the reference's variable names
(src/settings/settings.go:10-48)."""

import pytest

from api_ratelimit_tpu.settings import Settings, new_settings


class TestSettings:
    def test_defaults(self):
        s = new_settings({})
        assert s.port == 8080
        assert s.grpc_port == 8081
        assert s.debug_port == 6070
        assert s.use_statsd is True
        assert s.runtime_path == "/srv/runtime_data/current"
        assert s.near_limit_ratio == pytest.approx(0.8)
        assert s.expiration_jitter_max_seconds == 300
        assert s.local_cache_size_in_bytes == 0
        assert s.backend_type == "tpu"

    def test_reference_env_names(self):
        # a nomad-style env block (nomad/apigw-ratelimit/common.hcl)
        s = new_settings(
            {
                "GRPC_PORT": "9484",
                "PORT": "9486",
                "DEBUG_PORT": "9485",
                "USE_STATSD": "false",
                "RUNTIME_ROOT": "/data/runtime",
                "RUNTIME_SUBDIRECTORY": "ratelimit",
                "RUNTIME_WATCH_ROOT": "false",
                "LOG_LEVEL": "debug",
                "MAX_SLEEPING_ROUTINES": "64",
                "LOCAL_CACHE_SIZE_IN_BYTES": "1000000",
                "NEAR_LIMIT_RATIO": "0.9",
                "EXPIRATION_JITTER_MAX_SECONDS": "0",
            }
        )
        assert s.grpc_port == 9484
        assert s.use_statsd is False
        assert s.runtime_subdirectory == "ratelimit"
        assert s.runtime_watch_root is False
        assert s.max_sleeping_routines == 64
        assert s.local_cache_size_in_bytes == 1_000_000
        assert s.near_limit_ratio == pytest.approx(0.9)
        assert s.expiration_jitter_max_seconds == 0

    def test_go_duration_strings(self):
        s = new_settings(
            {
                "REDIS_PIPELINE_WINDOW": "75us",
                "TPU_BATCH_WINDOW": "500us",
            }
        )
        assert s.redis_pipeline_window == pytest.approx(75e-6)
        assert s.tpu_batch_window == pytest.approx(500e-6)
        assert new_settings({"TPU_BATCH_WINDOW": "2ms"}).tpu_batch_window == (
            pytest.approx(2e-3)
        )

    def test_bad_value_raises(self):
        with pytest.raises(ValueError, match="GRPC_PORT"):
            new_settings({"GRPC_PORT": "not-a-port"})
        with pytest.raises(ValueError, match="USE_STATSD"):
            new_settings({"USE_STATSD": "maybe"})

    def test_empty_string_keeps_default(self):
        s = new_settings({"STATSD_HOST": ""})
        assert s.statsd_host == "localhost"

    def test_tpu_knobs(self):
        s = new_settings(
            {
                "BACKEND_TYPE": "tpu",
                "TPU_SLAB_SLOTS": "8388608",
                "TPU_BATCH_LIMIT": "32768",
                "TPU_MESH_DEVICES": "4",
                "TPU_USE_PALLAS": "false",
            }
        )
        assert s.tpu_slab_slots == 1 << 23
        assert s.tpu_batch_limit == 32768
        assert s.tpu_mesh_devices == 4
        assert s.tpu_use_pallas is False

    def test_hotpath_knobs(self):
        s = new_settings(
            {
                "TPU_PRECOMPILE": "false",
                "TPU_BUCKETS": "16,256,4096",
                "HOST_FAST_PATH": "false",
            }
        )
        assert s.tpu_precompile is False
        assert s.tpu_buckets == "16,256,4096"
        assert s.buckets() == (16, 256, 4096)
        assert s.host_fast_path is False

    def test_hotpath_defaults(self):
        s = Settings()
        assert s.tpu_precompile is True
        assert s.host_fast_path is True
        assert s.dispatch_loop is True  # device-owner loop is the default
        assert s.buckets() is None  # engine default ladder

    def test_dispatch_loop_knob(self):
        # the rollback arm (leader-collects batcher), HOST_FAST_PATH style
        assert new_settings({"DISPATCH_LOOP": "false"}).dispatch_loop is False
        assert new_settings({"DISPATCH_LOOP": "on"}).dispatch_loop is True
        with pytest.raises(ValueError, match="DISPATCH_LOOP"):
            new_settings({"DISPATCH_LOOP": "sideways"})

    def test_journey_knobs(self):
        s = new_settings(
            {
                "JOURNEY_RECORDER_ENABLED": "false",
                "JOURNEY_SLOW_MS": "25.5",
                "JOURNEY_RETAIN": "512",
                "JOURNEY_RING": "32",
            }
        )
        assert s.journey_recorder_enabled is False
        assert s.journey_slow_ms == pytest.approx(25.5)
        assert s.journey_retain == 512
        assert s.journey_ring == 32
        assert s.journey_config() == (False, 25.5, 512, 32)

    def test_journey_defaults(self):
        s = new_settings({})
        # recorder on, live-p99 slow threshold, bounded buffers
        assert s.journey_config() == (True, 0.0, 256, 64)
        assert s.tpu_profile_dir == ""  # /debug/profile disabled

    def test_journey_junk_fails_boot(self):
        with pytest.raises(ValueError, match="JOURNEY_SLOW_MS"):
            new_settings({"JOURNEY_SLOW_MS": "-1"}).journey_config()
        with pytest.raises(ValueError, match="JOURNEY_RETAIN"):
            new_settings({"JOURNEY_RETAIN": "0"}).journey_config()
        with pytest.raises(ValueError, match="JOURNEY_RING"):
            new_settings({"JOURNEY_RING": "-4"}).journey_config()
        # non-numeric junk fails at parse time, like every other knob
        with pytest.raises(ValueError, match="JOURNEY_RETAIN"):
            new_settings({"JOURNEY_RETAIN": "many"})
        with pytest.raises(ValueError, match="JOURNEY_RECORDER_ENABLED"):
            new_settings({"JOURNEY_RECORDER_ENABLED": "maybe"})

    def test_tpu_profile_dir_knob(self):
        s = new_settings({"TPU_PROFILE_DIR": "/var/tmp/tpu-traces"})
        assert s.tpu_profile_dir == "/var/tmp/tpu-traces"

    def test_buckets_junk_fails_boot(self):
        for junk in ("abc", "128,xyz", "0", "-8,128", ","):
            with pytest.raises(ValueError, match="TPU_BUCKETS"):
                new_settings({"TPU_BUCKETS": junk}).buckets()

    def test_buckets_sorted(self):
        assert new_settings({"TPU_BUCKETS": "4096,16"}).buckets() == (16, 4096)

    def test_dataclass_is_plain(self):
        assert Settings().port == 8080

    def test_metrics_knobs(self):
        s = new_settings(
            {
                "DEBUG_METRICS_ENABLED": "false",
                "METRICS_LATENCY_BUCKETS_MS": "5, 0.5,1,100",
            }
        )
        assert s.debug_metrics_enabled is False
        assert s.latency_buckets() == (0.5, 1.0, 5.0, 100.0)  # sorted
        # default: endpoint on, store-default ladder
        assert Settings().debug_metrics_enabled is True
        assert Settings().latency_buckets() is None

    def test_metrics_buckets_junk_raises(self):
        with pytest.raises(ValueError):
            new_settings(
                {"METRICS_LATENCY_BUCKETS_MS": "1,abc"}
            ).latency_buckets()
        with pytest.raises(ValueError):
            new_settings(
                {"METRICS_LATENCY_BUCKETS_MS": "-1,5"}
            ).latency_buckets()


class TestResilienceSettings:
    """The PR-2 resilience knobs: sidecar retry/deadline/breaker, the
    FAILURE_MODE_DENY ladder, and FAULT_INJECT parsing — junk must fail
    boot like a typo'd bucket ladder."""

    def test_sidecar_resilience_env_names(self):
        s = new_settings(
            {
                "SIDECAR_CONNECT_TIMEOUT": "250ms",
                "SIDECAR_RPC_DEADLINE": "2s",
                "SIDECAR_RETRIES": "4",
                "SIDECAR_RETRY_BACKOFF": "5ms",
                "SIDECAR_RETRY_BACKOFF_MAX": "100ms",
                "SIDECAR_BREAKER_THRESHOLD": "3",
                "SIDECAR_BREAKER_RESET": "500ms",
            }
        )
        assert s.sidecar_connect_timeout == pytest.approx(0.25)
        assert s.sidecar_rpc_deadline == pytest.approx(2.0)
        assert s.sidecar_retries == 4
        assert s.sidecar_retry_backoff == pytest.approx(5e-3)
        assert s.sidecar_retry_backoff_max == pytest.approx(0.1)
        assert s.sidecar_breaker_threshold == 3
        assert s.sidecar_breaker_reset == pytest.approx(0.5)

    def test_resilience_defaults(self):
        s = new_settings({})
        assert s.failure_mode() is None  # legacy raise-through
        assert s.fault_rules() == []
        assert s.sidecar_retries == 2
        assert s.sidecar_breaker_threshold == 5

    def test_failure_mode_ladder_values(self):
        # upstream boolean parity: true = deny-all, false = fail-open
        assert new_settings({"FAILURE_MODE_DENY": "true"}).failure_mode() == "deny"
        assert new_settings({"FAILURE_MODE_DENY": "deny"}).failure_mode() == "deny"
        assert (
            new_settings({"FAILURE_MODE_DENY": "false"}).failure_mode()
            == "allow"
        )
        assert (
            new_settings({"FAILURE_MODE_DENY": "allow"}).failure_mode()
            == "allow"
        )
        assert (
            new_settings({"FAILURE_MODE_DENY": "degraded"}).failure_mode()
            == "degraded"
        )

    def test_failure_mode_junk_raises(self):
        with pytest.raises(ValueError, match="FAILURE_MODE_DENY"):
            new_settings({"FAILURE_MODE_DENY": "maybe"}).failure_mode()

    def test_fault_inject_spec_parses(self):
        s = new_settings(
            {
                "FAULT_INJECT": (
                    "sidecar.submit:error:0.2,sidecar.submit:delay_ms:500"
                ),
                "FAULT_INJECT_SEED": "7",
            }
        )
        rules = s.fault_rules()
        assert [(r.site, r.kind, r.value) for r in rules] == [
            ("sidecar.submit", "error", 0.2),
            ("sidecar.submit", "delay_ms", 500.0),
        ]
        assert s.fault_inject_seed == 7

    def test_fault_inject_junk_fails_boot(self):
        for spec in (
            "sidecar.submit:error",  # missing value
            "sidecar.submit:explode:0.5",  # unknown kind
            "sidecar.submit:error:1.5",  # probability out of range
            "sidecar.submit:error:zero",  # non-numeric value
            "BadSite:error:0.5",  # site convention
            "sidecar.submit:delay_ms:-1",  # negative delay
        ):
            with pytest.raises(ValueError, match="FAULT_INJECT"):
                new_settings({"FAULT_INJECT": spec}).fault_rules()

    def test_snapshot_knob_env_names(self):
        s = new_settings(
            {
                "SLAB_SNAPSHOT_DIR": "/var/lib/ratelimit/snapshots",
                "SLAB_SNAPSHOT_INTERVAL_MS": "2500",
                "SLAB_SNAPSHOT_STALE_AFTER_MS": "30000",
            }
        )
        assert s.slab_snapshot_dir == "/var/lib/ratelimit/snapshots"
        assert s.slab_snapshot_interval_ms == pytest.approx(2500.0)
        assert s.slab_snapshot_stale_after_ms == pytest.approx(30000.0)
        assert s.snapshot_config() == (
            "/var/lib/ratelimit/snapshots",
            2500.0,
            30000.0,
        )

    def test_snapshot_defaults_disabled(self):
        s = new_settings({})
        directory, interval_ms, stale_ms = s.snapshot_config()
        assert directory == ""  # empty dir = warm restart off
        assert interval_ms == pytest.approx(10_000.0)
        # staleness defaults to three intervals
        assert stale_ms == pytest.approx(30_000.0)

    def test_snapshot_junk_fails_boot(self):
        with pytest.raises(ValueError, match="SLAB_SNAPSHOT_INTERVAL_MS"):
            new_settings(
                {"SLAB_SNAPSHOT_INTERVAL_MS": "0"}
            ).snapshot_config()
        with pytest.raises(ValueError, match="SLAB_SNAPSHOT_INTERVAL_MS"):
            new_settings(
                {"SLAB_SNAPSHOT_INTERVAL_MS": "-5"}
            ).snapshot_config()
        with pytest.raises(ValueError, match="SLAB_SNAPSHOT_STALE_AFTER_MS"):
            new_settings(
                {"SLAB_SNAPSHOT_STALE_AFTER_MS": "-1"}
            ).snapshot_config()
        # staleness tighter than the write cadence would flap the probe
        with pytest.raises(ValueError, match="SLAB_SNAPSHOT_STALE_AFTER_MS"):
            new_settings(
                {
                    "SLAB_SNAPSHOT_INTERVAL_MS": "10000",
                    "SLAB_SNAPSHOT_STALE_AFTER_MS": "500",
                }
            ).snapshot_config()
        # non-numeric junk fails at parse time, like every other knob
        with pytest.raises(ValueError, match="SLAB_SNAPSHOT_INTERVAL_MS"):
            new_settings({"SLAB_SNAPSHOT_INTERVAL_MS": "soon"})

    def test_snapshot_fault_sites_parse_from_env(self):
        s = new_settings(
            {
                "FAULT_INJECT": (
                    "snapshot.write:torn_write:1.0,snapshot.load:corrupt:0.5"
                )
            }
        )
        rules = s.fault_rules()
        assert [(r.site, r.kind) for r in rules] == [
            ("snapshot.write", "torn_write"),
            ("snapshot.load", "corrupt"),
        ]


class TestLeaseSettings:
    def test_defaults_are_the_rollback_arm(self):
        s = Settings()
        assert s.lease_enabled is False  # byte-identical pre-lease pipeline
        assert s.lease_min == 8
        assert s.lease_max == 1024
        assert s.lease_ttl_fraction == pytest.approx(0.25)
        assert s.lease_near_limit_ratio == pytest.approx(0.9)
        assert s.lease_config() == (False, 8, 1024, 0.25, 0.9)

    def test_env_parsing(self):
        s = new_settings(
            {
                "LEASE_ENABLED": "true",
                "LEASE_MIN": "2",
                "LEASE_MAX": "256",
                "LEASE_TTL_FRACTION": "0.5",
                "LEASE_NEAR_LIMIT_RATIO": "0.8",
            }
        )
        assert s.lease_config() == (True, 2, 256, 0.5, 0.8)

    def test_junk_rejected(self):
        with pytest.raises(ValueError, match="LEASE_ENABLED"):
            new_settings({"LEASE_ENABLED": "sideways"})
        with pytest.raises(ValueError, match="LEASE_MIN"):
            new_settings({"LEASE_MIN": "four"})
        with pytest.raises(ValueError, match="LEASE_MIN"):
            new_settings({"LEASE_MIN": "0"}).lease_config()
        with pytest.raises(ValueError, match="LEASE_MAX"):
            new_settings({"LEASE_MIN": "64", "LEASE_MAX": "8"}).lease_config()
        with pytest.raises(ValueError, match="LEASE_TTL_FRACTION"):
            new_settings({"LEASE_TTL_FRACTION": "0"}).lease_config()
        with pytest.raises(ValueError, match="LEASE_TTL_FRACTION"):
            new_settings({"LEASE_TTL_FRACTION": "1.5"}).lease_config()
        with pytest.raises(ValueError, match="LEASE_NEAR_LIMIT_RATIO"):
            new_settings(
                {"LEASE_NEAR_LIMIT_RATIO": "-0.1"}
            ).lease_config()


class TestHotkeySettings:
    """HOTKEYS_* knobs (ops/sketch.py heavy-hitter telemetry), following
    the lease_config() junk-rejection pattern."""

    def test_defaults(self):
        s = Settings()
        assert s.hotkeys_enabled is True
        assert s.hotkey_k == 16
        assert s.hotkey_lanes == 128
        assert s.hotkey_config() == (True, 16, 128)

    def test_env_parsing(self):
        s = new_settings(
            {
                "HOTKEYS_ENABLED": "false",
                "HOTKEY_K": "8",
                "HOTKEY_LANES": "64",
            }
        )
        assert s.hotkey_config() == (False, 8, 64)

    def test_junk_rejected(self):
        with pytest.raises(ValueError, match="HOTKEYS_ENABLED"):
            new_settings({"HOTKEYS_ENABLED": "sideways"})
        with pytest.raises(ValueError, match="HOTKEY_K"):
            new_settings({"HOTKEY_K": "many"})
        with pytest.raises(ValueError, match="HOTKEY_K"):
            new_settings({"HOTKEY_K": "0"}).hotkey_config()
        with pytest.raises(ValueError, match="HOTKEY_LANES"):
            new_settings({"HOTKEY_LANES": "100"}).hotkey_config()
        with pytest.raises(ValueError, match="HOTKEY_LANES"):
            new_settings({"HOTKEY_LANES": "-128"}).hotkey_config()
        with pytest.raises(ValueError, match="HOTKEY_K"):
            new_settings(
                {"HOTKEY_K": "64", "HOTKEY_LANES": "32"}
            ).hotkey_config()


class TestVictimSettings:
    """VICTIM_* knobs (backends/victim.py host-RAM victim tier),
    following the lease_config() junk-rejection pattern: a typo'd bound
    must fail the boot, never silently become 'no tier' (live-eviction
    counter loss would come back without a trace)."""

    def test_defaults(self):
        s = Settings()
        assert s.victim_tier_enabled is False
        assert s.victim_max_rows == 1 << 20
        assert s.victim_watermark == 0.85
        assert s.victim_config() == (False, 1 << 20, 0.85)

    def test_env_parsing(self):
        s = new_settings(
            {
                "VICTIM_TIER_ENABLED": "true",
                "VICTIM_MAX_ROWS": "4096",
                "VICTIM_WATERMARK": "0.5",
            }
        )
        assert s.victim_config() == (True, 4096, 0.5)

    def test_junk_rejected(self):
        with pytest.raises(ValueError, match="VICTIM_TIER_ENABLED"):
            new_settings({"VICTIM_TIER_ENABLED": "sideways"})
        with pytest.raises(ValueError, match="VICTIM_MAX_ROWS"):
            new_settings({"VICTIM_MAX_ROWS": "many"})
        with pytest.raises(ValueError, match="VICTIM_MAX_ROWS"):
            new_settings({"VICTIM_MAX_ROWS": "0"}).victim_config()
        with pytest.raises(ValueError, match="VICTIM_MAX_ROWS"):
            new_settings({"VICTIM_MAX_ROWS": "-1"}).victim_config()
        with pytest.raises(ValueError, match="VICTIM_WATERMARK"):
            new_settings({"VICTIM_WATERMARK": "1.5"}).victim_config()
        with pytest.raises(ValueError, match="VICTIM_WATERMARK"):
            new_settings({"VICTIM_WATERMARK": "0"}).victim_config()


class TestReplicationSettings:
    """SIDECAR_ADDRS / REPL_* knobs (persist/replication.py), following
    the lease_config() junk-rejection pattern: a typo'd knob fails the
    boot, never silently becomes a different redundancy posture."""

    def test_defaults_disable_replication(self):
        s = new_settings({})
        assert s.repl_config() == ("", 100.0, 500.0)
        assert s.sidecar_addresses() == [s.sidecar_socket]
        assert s.repl_peer_address() is None

    def test_addrs_parse_and_order_preserved(self):
        s = new_settings(
            {"SIDECAR_ADDRS": " /a.sock , tcp://h:9000 ,tls://x:1 "}
        )
        assert s.sidecar_addresses() == [
            "/a.sock",
            "tcp://h:9000",
            "tls://x:1",
        ]

    def test_peer_is_first_entry_that_is_not_self(self):
        s = new_settings(
            {
                "SIDECAR_SOCKET": "/b.sock",
                "SIDECAR_ADDRS": "/a.sock,/b.sock",
            }
        )
        assert s.repl_peer_address() == "/a.sock"

    def test_roles_accepted(self):
        for role in ("primary", "standby", "auto"):
            s = new_settings(
                {
                    "REPL_ROLE": role,
                    "SIDECAR_SOCKET": "/me.sock",
                    "SIDECAR_ADDRS": "/me.sock,/peer.sock",
                }
            )
            assert s.repl_config()[0] == role

    def test_junk_role_fails_boot(self):
        s = new_settings({"REPL_ROLE": "leader"})
        with pytest.raises(ValueError, match="REPL_ROLE"):
            s.repl_config()

    def test_junk_interval_fails_boot(self):
        s = new_settings({"REPL_INTERVAL_MS": "0"})
        with pytest.raises(ValueError, match="REPL_INTERVAL_MS"):
            s.repl_config()
        with pytest.raises(ValueError, match="REPL_INTERVAL_MS"):
            new_settings({"REPL_INTERVAL_MS": "soon"})

    def test_max_lag_below_interval_fails_boot(self):
        s = new_settings(
            {"REPL_INTERVAL_MS": "100", "REPL_MAX_LAG_MS": "50"}
        )
        with pytest.raises(ValueError, match="REPL_MAX_LAG_MS"):
            s.repl_config()

    def test_max_lag_defaults_to_five_intervals(self):
        s = new_settings({"REPL_INTERVAL_MS": "40"})
        assert s.repl_config() == ("", 40.0, 200.0)

    def test_standby_without_peer_fails_boot(self):
        s = new_settings(
            {
                "REPL_ROLE": "standby",
                "SIDECAR_SOCKET": "/me.sock",
                "SIDECAR_ADDRS": "/me.sock",
            }
        )
        with pytest.raises(ValueError, match="peer"):
            s.repl_config()

    def test_malformed_addr_entry_fails_boot(self):
        s = new_settings({"SIDECAR_ADDRS": "tcp://nohost"})
        with pytest.raises(ValueError, match="SIDECAR_ADDRS"):
            s.sidecar_addresses()


class TestShmRingSettings:
    """SHM_RINGS / FRONTEND_PROCS knobs (backends/shm_ring.py +
    cmd/service_cmd.py): derivation rules for the control socket and the
    junk-fails-boot discipline every other knob follows."""

    def test_defaults(self):
        s = Settings()
        assert s.shm_rings is True
        assert s.frontend_procs == 1  # single-process legacy boot
        assert s.shm_ring_rows == 4096
        assert s.frontend_procs_count() == 1
        assert s.shm_ring_rows_count() == 4096

    def test_env_parsing(self):
        s = new_settings(
            {
                "SHM_RINGS": "false",
                "SHM_CONTROL_SOCK": "/tmp/ctl.sock",
                "SHM_RING_ROWS": "8192",
                "FRONTEND_PROCS": "4",
            }
        )
        assert s.shm_rings is False
        assert s.shm_control_sock == "/tmp/ctl.sock"
        assert s.shm_ring_rows_count() == 8192
        assert s.frontend_procs_count() == 4

    def test_control_path_derivation(self):
        s = Settings()
        s.sidecar_socket = "/run/rl/owner.sock"
        assert s.shm_control_path() == "/run/rl/owner.sock.shmctl"
        # explicit path wins
        s.shm_control_sock = "/tmp/x.sock"
        assert s.shm_control_path() == "/tmp/x.sock"
        # rollback arm derives nothing
        s.shm_rings = False
        assert s.shm_control_path() == ""
        # shared memory cannot cross hosts: tcp/tls sidecars disable shm
        s.shm_rings = True
        s.shm_control_sock = ""
        s.sidecar_socket = "tcp://owner:7070"
        assert s.shm_control_path() == ""
        s.sidecar_socket = "tls://owner:7070"
        assert s.shm_control_path() == ""

    def test_junk_rejected(self):
        with pytest.raises(ValueError, match="SHM_RINGS"):
            new_settings({"SHM_RINGS": "sideways"})
        with pytest.raises(ValueError, match="FRONTEND_PROCS"):
            new_settings({"FRONTEND_PROCS": "two"})
        with pytest.raises(ValueError, match="FRONTEND_PROCS"):
            new_settings({"FRONTEND_PROCS": "0"}).frontend_procs_count()
        with pytest.raises(ValueError, match="BACKEND_TYPE"):
            new_settings(
                {"FRONTEND_PROCS": "2", "BACKEND_TYPE": "memory"}
            ).frontend_procs_count()
        with pytest.raises(ValueError, match="SHM_RING_ROWS"):
            new_settings({"SHM_RING_ROWS": "8"}).shm_ring_rows_count()


class TestClusterSettings:
    """PARTITIONS / PARTITION_ADDRS / PARTITION_ROUTE_SETS /
    RESHARD_RATE_LIMIT_MB_S (cluster/)."""

    def test_defaults_are_the_rollback_arm(self):
        s = Settings()
        k, groups, route_sets, rate = s.cluster_config()
        assert k == 1
        assert groups == []
        assert route_sets == 256
        assert rate == 32.0

    def test_env_parsing(self):
        s = new_settings(
            {
                "PARTITIONS": "2",
                "PARTITION_ADDRS": (
                    "/run/p0a.sock,/run/p0b.sock;"
                    "tcp://h1:7070,tcp://h1:7071"
                ),
                "PARTITION_ROUTE_SETS": "512",
                "RESHARD_RATE_LIMIT_MB_S": "8.5",
            }
        )
        k, groups, route_sets, rate = s.cluster_config()
        assert k == 2
        assert groups == [
            ["/run/p0a.sock", "/run/p0b.sock"],
            ["tcp://h1:7070", "tcp://h1:7071"],
        ]
        assert route_sets == 512
        assert rate == 8.5
        # a sidecar discovers its own partition from the group listing
        # its socket; unlisted addresses discover nothing
        s.sidecar_socket = "/run/p0b.sock"
        assert s.cluster_partition_of(s.sidecar_socket) == 0
        assert s.cluster_partition_of("tcp://h1:7071") == 1
        assert s.cluster_partition_of("/run/elsewhere.sock") is None

    def test_junk_rejected(self):
        with pytest.raises(ValueError, match="PARTITIONS"):
            new_settings({"PARTITIONS": "two"})
        with pytest.raises(ValueError, match="PARTITIONS"):
            new_settings({"PARTITIONS": "0"}).cluster_config()
        with pytest.raises(ValueError, match="PARTITION_ROUTE_SETS"):
            new_settings({"PARTITION_ROUTE_SETS": "100"}).cluster_config()
        with pytest.raises(ValueError, match="RESHARD_RATE_LIMIT_MB_S"):
            new_settings({"RESHARD_RATE_LIMIT_MB_S": "0"}).cluster_config()
        # K>1 demands exactly K ';'-separated groups
        with pytest.raises(ValueError, match="groups"):
            new_settings(
                {"PARTITIONS": "2", "PARTITION_ADDRS": "/run/a.sock"}
            ).cluster_config()
        with pytest.raises(ValueError, match="PARTITION_ADDRS entry"):
            new_settings(
                {
                    "PARTITIONS": "2",
                    "PARTITION_ADDRS": "/run/a.sock;tcp://nope",
                }
            ).cluster_config()
        # more partitions than route sets cannot tile the space
        with pytest.raises(ValueError, match="cannot exceed"):
            new_settings(
                {
                    "PARTITIONS": "4",
                    "PARTITION_ROUTE_SETS": "2",
                    "PARTITION_ADDRS": "a;b;c;d",
                }
            ).cluster_config()


class TestFederationSettings:
    """FED_* knobs (cluster/federation.py global quota federation),
    following the lease_config() junk-rejection pattern: a typo'd
    membership must fail the boot, never silently become a different
    home assignment."""

    def test_defaults_are_the_rollback_arm(self):
        s = Settings()
        assert s.fed_enabled is False  # byte-identical pre-federation wire
        enabled, self_name, peers, mn, mx, interval, lag, ttl = (
            s.fed_config()
        )
        assert enabled is False
        assert self_name == "" and peers == {}
        assert (mn, mx) == (8, 1024)
        assert interval == pytest.approx(50.0)
        # 0 defaults resolve to multiples of the settle interval
        assert lag == pytest.approx(250.0)
        assert ttl == pytest.approx(500.0)

    def test_env_parsing(self):
        s = new_settings(
            {
                "FED_ENABLED": "true",
                "FED_SELF": "east",
                "FED_PEERS": " east=/run/e.sock , west=tcp://w:9000 ",
                "FED_SHARE_MIN": "2",
                "FED_SHARE_MAX": "64",
                "FED_SETTLE_INTERVAL_MS": "100",
                "FED_MAX_LAG_MS": "400",
                "FED_SHARE_TTL_MS": "1000",
            }
        )
        enabled, self_name, peers, mn, mx, interval, lag, ttl = (
            s.fed_config()
        )
        assert enabled is True
        assert self_name == "east"
        assert peers == {"east": "/run/e.sock", "west": "tcp://w:9000"}
        assert (mn, mx) == (2, 64)
        assert (interval, lag, ttl) == (100.0, 400.0, 1000.0)

    def test_junk_rejected(self):
        with pytest.raises(ValueError, match="FED_ENABLED"):
            new_settings({"FED_ENABLED": "sideways"})
        with pytest.raises(ValueError, match="FED_SHARE_MIN"):
            new_settings({"FED_SHARE_MIN": "four"})
        with pytest.raises(ValueError, match="FED_SHARE_MIN"):
            new_settings({"FED_SHARE_MIN": "0"}).fed_config()
        with pytest.raises(ValueError, match="FED_SHARE_MAX"):
            new_settings(
                {"FED_SHARE_MIN": "64", "FED_SHARE_MAX": "8"}
            ).fed_config()
        with pytest.raises(ValueError, match="FED_SETTLE_INTERVAL_MS"):
            new_settings({"FED_SETTLE_INTERVAL_MS": "0"}).fed_config()
        # a lag/ttl bound below the settle cadence would flap on every
        # pump — rejected, like REPL_MAX_LAG_MS below its interval
        with pytest.raises(ValueError, match="FED_MAX_LAG_MS"):
            new_settings(
                {"FED_SETTLE_INTERVAL_MS": "100", "FED_MAX_LAG_MS": "50"}
            ).fed_config()
        with pytest.raises(ValueError, match="FED_MAX_LAG_MS"):
            new_settings({"FED_MAX_LAG_MS": "-1"}).fed_config()
        with pytest.raises(ValueError, match="FED_SHARE_TTL_MS"):
            new_settings(
                {"FED_SETTLE_INTERVAL_MS": "100", "FED_SHARE_TTL_MS": "50"}
            ).fed_config()

    def test_enabled_membership_junk_rejected(self):
        with pytest.raises(ValueError, match="FED_SELF"):
            new_settings(
                {"FED_ENABLED": "true", "FED_PEERS": "a=/a,b=/b"}
            ).fed_config()
        with pytest.raises(ValueError, match="FED_PEERS"):
            new_settings(
                {"FED_ENABLED": "true", "FED_SELF": "a"}
            ).fed_config()
        with pytest.raises(ValueError, match="name=address"):
            new_settings(
                {
                    "FED_ENABLED": "true",
                    "FED_SELF": "a",
                    "FED_PEERS": "a=/a,b",
                }
            ).fed_config()
        with pytest.raises(ValueError, match="duplicate"):
            new_settings(
                {
                    "FED_ENABLED": "true",
                    "FED_SELF": "a",
                    "FED_PEERS": "a=/a,a=/b",
                }
            ).fed_config()
        with pytest.raises(ValueError, match="address"):
            new_settings(
                {
                    "FED_ENABLED": "true",
                    "FED_SELF": "a",
                    "FED_PEERS": "a=/a,b=tcp://nope",
                }
            ).fed_config()
        with pytest.raises(ValueError, match="at least two"):
            new_settings(
                {
                    "FED_ENABLED": "true",
                    "FED_SELF": "a",
                    "FED_PEERS": "a=/a",
                }
            ).fed_config()
        # self must be part of the membership it hashes over
        with pytest.raises(ValueError, match="FED_SELF"):
            new_settings(
                {
                    "FED_ENABLED": "true",
                    "FED_SELF": "c",
                    "FED_PEERS": "a=/a,b=/b",
                }
            ).fed_config()
