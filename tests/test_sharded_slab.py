"""Multi-chip sharded slab tests on the virtual 8-device CPU mesh.

Parity contract: sharding only selects WHICH device's sub-table a key lives
in (parallel/sharded_slab.py); decisions must match both the single-device
slab and the pure-Python memory oracle exactly, the way Redis Cluster gives
the reference identical semantics to a single Redis (src/redis/
driver_impl.go:104-110).
"""

import random

import jax
import numpy as np
import pytest

from api_ratelimit_tpu.backends import MemoryRateLimitCache
from api_ratelimit_tpu.backends.tpu import TpuRateLimitCache
from api_ratelimit_tpu.limiter import BaseRateLimiter
from api_ratelimit_tpu.models import Code, Descriptor, RateLimitRequest, Unit
from api_ratelimit_tpu.models.config import RateLimit, new_rate_limit_stats
from api_ratelimit_tpu.models.response import RateLimitValue
from api_ratelimit_tpu.parallel import ShardedSlabEngine, make_mesh
from api_ratelimit_tpu.parallel import sharded_slab as _sharded_slab
from api_ratelimit_tpu.stats import Store, TestSink
from api_ratelimit_tpu.utils import FakeTimeSource

pytestmark = pytest.mark.skipif(
    _sharded_slab.shard_map is None,
    reason="this jax has neither jax.shard_map nor "
    "jax.experimental.shard_map",
)


def _fmix32(x: np.ndarray) -> np.ndarray:
    """murmur3 finalizer — a bijection on uint32 (same expansion the bench
    uses to turn staged key ids into well-mixed fingerprint halves)."""
    x = np.asarray(x, dtype=np.uint32)
    x = x ^ (x >> np.uint32(16))
    x = x * np.uint32(0x85EBCA6B)
    x = x ^ (x >> np.uint32(13))
    x = x * np.uint32(0xC2B2AE35)
    return x ^ (x >> np.uint32(16))


def make_limit(store, rpu, unit, key):
    return RateLimit(
        full_key=key,
        stats=new_rate_limit_stats(store, key),
        limit=RateLimitValue(requests_per_unit=rpu, unit=unit),
    )


def req(*pairs, hits=1, domain="domain"):
    return RateLimitRequest(
        domain=domain,
        descriptors=tuple(Descriptor.of(p) for p in pairs),
        hits_addend=hits,
    )


def make_sharded_cache(ts, mesh, n_slots=1 << 15):
    base = BaseRateLimiter(ts, local_cache=None, near_limit_ratio=0.8)
    return TpuRateLimitCache(
        base,
        n_slots=n_slots,
        batch_window_seconds=0.0,
        buckets=(128, 1024),
        max_batch=1024,
        use_pallas=False,
        mesh=mesh,
    )


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must force the 8-device CPU mesh"
    return make_mesh()


class TestShardedEngine:
    def test_state_spans_mesh(self, mesh):
        eng = ShardedSlabEngine(mesh=mesh, n_slots_global=8 * 256)
        assert eng._state.shape == (8 * 256, 8)
        assert len(eng._state.sharding.device_set) == 8

    def test_bad_slot_split_rejected(self, mesh):
        with pytest.raises(ValueError):
            ShardedSlabEngine(mesh=mesh, n_slots_global=8 * 300)

    def test_non_fixed_launch_flips_pallas_guard(self, mesh):
        """The sticky algorithms guard, mesh edition: a use_pallas engine
        whose launch carries a non-fixed algorithm id must rebuild its
        step functions on the XLA twin BEFORE dispatch — the Mosaic body
        is fixed_window-only, so without the flip sliding/GCRA/release
        rows would run fixed-window math on multi-chip deployments. (On
        this CPU mesh a pallas compile would fail outright, so the
        correct counters below also prove no pallas program ever built.)"""
        from api_ratelimit_tpu.ops.slab import (
            ALGO_CONC_RELEASE,
            ALGO_CONCURRENCY,
            ALGO_SHIFT,
            ALGO_SLIDING_WINDOW,
        )

        eng = ShardedSlabEngine(
            mesh=mesh, n_slots_global=8 * 256, use_pallas=True
        )
        assert eng._use_pallas is True and eng.algos_seen is False

        def packed_one(algo, hits=1, limit=10, now=1_000_000):
            p = np.zeros((7, 128), dtype=np.uint32)
            p[0, 0], p[1, 0] = 1234, 0xABCD0001
            p[2, 0] = hits
            p[3, 0] = limit
            p[4, 0] = 60 | (algo << ALGO_SHIFT)
            p[6, 0] = now
            p[6, 1] = np.float32(0.8).view(np.uint32)
            p[6, 2] = np.float32(1.0).view(np.uint32)
            return p

        # sliding key: two launches in one window must accumulate 1 -> 2
        # (the fixed-window Mosaic body misreading the divider word would
        # never see the same window twice for a ~2^28-second "window")
        after = eng.step_after_compact(packed_one(ALGO_SLIDING_WINDOW), 0xFFFF)
        assert eng.algos_seen is True and eng._use_pallas is False
        assert int(after[0]) == 1
        after = eng.step_after_compact(packed_one(ALGO_SLIDING_WINDOW), 0xFFFF)
        assert int(after[0]) == 2

        # concurrency on a second key: acquire, release (wire id 4 must
        # DECREMENT, not increment), acquire again lands back at 1 + 1
        def conc(algo):
            p = packed_one(algo, limit=3)
            p[0, 0], p[1, 0] = 5678, 0xBEEF0001
            return p

        assert int(eng.step_after_compact(conc(ALGO_CONCURRENCY), 0xFFFF)[0]) == 1
        eng.step_after_compact(conc(ALGO_CONC_RELEASE), 0xFFFF)
        assert int(eng.step_after_compact(conc(ALGO_CONCURRENCY), 0xFFFF)[0]) == 1

    def test_restored_algorithm_rows_flip_pallas_guard(self, mesh):
        eng = ShardedSlabEngine(
            mesh=mesh, n_slots_global=8 * 256, use_pallas=True
        )
        tables = [np.zeros((256, 8), dtype=np.uint32) for _ in range(8)]
        # one restored GCRA row: the table is no longer pallas-safe even
        # before the first non-fixed launch
        tables[3][0] = (
            1, 2, 3, 999_970, 1_000_050, 60 | (2 << 28), 1_000_030, 0,
        )
        eng.import_tables(tables)
        assert eng.algos_seen is True and eng._use_pallas is False

    def test_over_limit_sequence(self, mesh):
        ts = FakeTimeSource(1_000_000)
        store = Store(TestSink())
        cache = make_sharded_cache(ts, mesh)
        limit = make_limit(store, 3, Unit.MINUTE, "k_v")
        for want in [Code.OK, Code.OK, Code.OK, Code.OVER_LIMIT]:
            resp = cache.do_limit(req(("k", "v")), [limit])
            assert resp.descriptor_statuses[0].code == want
        cache.close()

    def test_keys_spread_and_count_independently(self, mesh):
        ts = FakeTimeSource(1_000_000)
        store = Store(TestSink())
        cache = make_sharded_cache(ts, mesh)
        limits = [make_limit(store, 5, Unit.HOUR, f"k_{i}") for i in range(64)]
        descriptors = [("k", str(i)) for i in range(64)]
        # Warm round: 64 distinct keys INSERT in one batch — two keys whose
        # set and way-preference collide may drop one write (the documented
        # fail-open in-batch contention undercount, counted in `drops`).
        # Advancing into the next hour window makes every key resident
        # (rows survive, the window rolls to base 0), so the strict rounds
        # below all take the fingerprint-MATCH path, where a same-batch
        # winner is never displaced and counting is exact.
        cache.do_limit(req(*descriptors), limits)
        ts.advance(3600 - ts.unix_now() % 3600)
        # 64 distinct resident keys in one batch, repeated: each counts on
        # its own shard, independently and exactly
        for round_no in range(6):
            resp = cache.do_limit(req(*descriptors), limits)
            want = Code.OK if round_no < 5 else Code.OVER_LIMIT
            for s in resp.descriptor_statuses:
                assert s.code == want, round_no
        cache.close()

    def test_parity_vs_memory_oracle_random_stream(self, mesh):
        rng = random.Random(7)
        ts_a, ts_b = FakeTimeSource(1_700_000_000), FakeTimeSource(1_700_000_000)
        store = Store(TestSink())
        sharded = make_sharded_cache(ts_a, mesh)
        base_b = BaseRateLimiter(ts_b, local_cache=None, near_limit_ratio=0.8)
        oracle = MemoryRateLimitCache(base_b)

        limits_a = [make_limit(store, 10, Unit.MINUTE, f"u_{i}") for i in range(20)]
        limits_b = [make_limit(store, 10, Unit.MINUTE, f"u_{i}") for i in range(20)]

        for step in range(120):
            idxs = rng.sample(range(20), k=rng.randint(1, 6))
            descriptors = [("user", str(i)) for i in idxs]
            ra = sharded.do_limit(
                req(*descriptors), [limits_a[i] for i in idxs]
            )
            rb = oracle.do_limit(
                req(*descriptors), [limits_b[i] for i in idxs]
            )
            for sa, sb in zip(ra.descriptor_statuses, rb.descriptor_statuses):
                assert (sa.code, sa.limit_remaining, sa.duration_until_reset) == (
                    sb.code,
                    sb.limit_remaining,
                    sb.duration_until_reset,
                ), f"diverged at step {step}"
            if rng.random() < 0.3:
                ts_a.advance(7)
                ts_b.advance(7)
        sharded.close()

    def test_duplicate_keys_in_one_batch_serialize(self, mesh):
        ts = FakeTimeSource(1_000_000)
        store = Store(TestSink())
        cache = make_sharded_cache(ts, mesh)
        limit1 = make_limit(store, 3, Unit.MINUTE, "dup")
        limit2 = make_limit(store, 3, Unit.MINUTE, "dup")
        # 4 hits on the same key in ONE request: 3 OK-ish then OVER
        resp = cache.do_limit(
            req(("d", "x"), ("d", "x"), ("d", "x"), ("d", "x")),
            [limit1, limit2, limit1, limit2],
        )
        codes = [s.code for s in resp.descriptor_statuses]
        assert codes == [Code.OK, Code.OK, Code.OK, Code.OVER_LIMIT]
        cache.close()

    def test_window_rollover(self, mesh):
        ts = FakeTimeSource(1_000_000)
        store = Store(TestSink())
        cache = make_sharded_cache(ts, mesh)
        limit = make_limit(store, 2, Unit.SECOND, "s")
        assert (
            cache.do_limit(req(("a", "b"), hits=2), [limit])
            .descriptor_statuses[0]
            .code
            == Code.OK
        )
        assert (
            cache.do_limit(req(("a", "b")), [limit]).descriptor_statuses[0].code
            == Code.OVER_LIMIT
        )
        ts.advance(1)  # next fixed window
        assert (
            cache.do_limit(req(("a", "b")), [limit]).descriptor_statuses[0].code
            == Code.OK
        )
        cache.close()


class TestCompactedMode:
    """step_after_compact (host owner-routing, per-shard buckets) must be
    decision-identical to the replicated step_after on the same stream —
    the compaction only changes WHERE items are computed, never the result
    (VERDICT round 1 weak #4: adding chips must add throughput, which
    requires each chip to see only its ~b/n share)."""

    @staticmethod
    def _packed(rng, b, now, limit=5):
        from api_ratelimit_tpu.ops.slab import (
            ROW_DIVIDER,
            ROW_FP_HI,
            ROW_FP_LO,
            ROW_HITS,
            ROW_LIMIT,
            ROW_SCALARS,
        )

        packed = np.zeros((7, b), dtype=np.uint32)
        ids = rng.integers(0, 200, size=b).astype(np.uint32)
        # two independent murmur-finalizer bijections, the same quality the
        # real fingerprint path (ops/hashing.py xxhash) delivers: the slab's
        # set/way/shard selectors read disjoint LOW-bit fields, so a bare
        # `ids * odd-constant` expansion (whose low bits form a lattice)
        # would systematically collide way preferences that production
        # fingerprints never would
        packed[ROW_FP_LO] = _fmix32(ids)
        packed[ROW_FP_HI] = _fmix32(ids ^ np.uint32(0x9E3779B9))
        packed[ROW_HITS] = 1
        packed[ROW_HITS, b - 1] = 0  # one padding lane rides along
        packed[ROW_LIMIT] = limit
        packed[ROW_DIVIDER] = 60
        packed[ROW_SCALARS, 0] = np.uint32(now)
        packed[ROW_SCALARS, 1] = np.float32(0.8).view(np.uint32)
        return packed

    def test_identical_to_replicated_mode(self, mesh):
        rng = np.random.default_rng(3)
        now = 1_000_000
        replicated = ShardedSlabEngine(mesh=mesh, n_slots_global=8 * 1024)
        compacted = ShardedSlabEngine(mesh=mesh, n_slots_global=8 * 1024)
        for _ in range(5):
            packed = self._packed(rng, 512, now)
            a = replicated.step_after(packed, cap=0xFFFF)
            b = compacted.step_after_compact(packed, cap=0xFFFF)
            np.testing.assert_array_equal(np.asarray(a, dtype=np.uint32), b)

    def test_modes_share_state(self, mesh):
        # same engine, alternating modes: counts continue seamlessly because
        # routing uses the same ownership function and the same sub-tables
        rng = np.random.default_rng(4)
        now = 1_000_000
        engine = ShardedSlabEngine(mesh=mesh, n_slots_global=8 * 1024)
        packed = self._packed(rng, 256, now)
        first = engine.step_after(packed, cap=0xFFFF)
        second = engine.step_after_compact(packed, cap=0xFFFF)
        valid = packed[2] > 0
        a1 = np.asarray(first, np.uint32)[valid]
        a2 = np.asarray(second)[valid]
        # counters never regress across modes, and every item whose counter
        # did NOT advance must trace to a counted in-batch contention drop
        # (two distinct random keys colliding on one way — the documented
        # fail-open undercount; the loser re-inserts from 0 next batch)
        assert (a2 >= a1).all()
        stuck = np.flatnonzero(a2 <= a1)
        drops = engine.health_snapshot(now=now)["drops"]
        from api_ratelimit_tpu.ops.slab import ROW_FP_HI, ROW_FP_LO

        fp = packed[ROW_FP_LO][valid].astype(np.uint64) | (
            packed[ROW_FP_HI][valid].astype(np.uint64) << np.uint64(32)
        )
        stuck_keys = len(set(fp[stuck].tolist()))
        assert stuck_keys <= drops
        # and the overwhelming majority advanced
        assert (a2 > a1).sum() >= a1.size - 8

    def test_skewed_batch_grows_bucket(self, mesh):
        # all items one key -> one shard owns the whole batch; the bucket
        # ladder grows past b/n and the result is still exact
        from api_ratelimit_tpu.ops.slab import ROW_FP_HI, ROW_FP_LO, ROW_HITS

        rng = np.random.default_rng(5)
        packed = self._packed(rng, 512, 1_000_000, limit=1000)
        packed[ROW_FP_LO] = 7
        packed[ROW_FP_HI] = 9
        packed[ROW_HITS] = 1
        engine = ShardedSlabEngine(mesh=mesh, n_slots_global=8 * 1024)
        out = engine.step_after_compact(packed, cap=0xFFFF)
        # duplicate serialization: counters 1..512 in arrival order
        np.testing.assert_array_equal(out, np.arange(1, 513, dtype=np.uint32))

    def test_health_flows_through_compacted_mode(self, mesh):
        engine = ShardedSlabEngine(mesh=mesh, n_slots_global=8 * 128)
        rng = np.random.default_rng(6)
        engine.step_after_compact(self._packed(rng, 512, 1_000_000))
        snap = engine.health_snapshot(now=1_000_000)
        assert snap["live_slots"] > 0
        assert snap["drops"] >= 0
        for k in ("evictions_expired", "evictions_window", "evictions_live"):
            assert snap[k] >= 0

    def test_launch_collect_split_matches_sync(self, mesh):
        """The double-buffered split (VERDICT r4 weak #2): two launches in
        flight before any collect must produce exactly what the synchronous
        calls produce — the state chain serializes the device work, and each
        token's routing permutation reassembles its own batch."""
        rng = np.random.default_rng(7)
        now = 1_000_000
        sync = ShardedSlabEngine(mesh=mesh, n_slots_global=8 * 1024)
        split = ShardedSlabEngine(mesh=mesh, n_slots_global=8 * 1024)
        batches = [self._packed(rng, 256, now) for _ in range(4)]
        want = [sync.step_after_compact(p, cap=0xFFFF) for p in batches]

        tokens = [split.launch_after_compact(p, cap=0xFFFF) for p in batches[:2]]
        got = [split.collect_after_compact(tokens[0])]
        tokens.append(split.launch_after_compact(batches[2], cap=0xFFFF))
        got.append(split.collect_after_compact(tokens[1]))
        tokens.append(split.launch_after_compact(batches[3], cap=0xFFFF))
        got.extend(split.collect_after_compact(t) for t in tokens[2:])
        for w, g in zip(want, got):
            np.testing.assert_array_equal(w, g)

    def test_empty_batch_launch_collect(self, mesh):
        # all lanes padding: launch short-circuits, collect returns zeros
        engine = ShardedSlabEngine(mesh=mesh, n_slots_global=8 * 1024)
        packed = self._packed(np.random.default_rng(8), 64, 1_000_000)
        packed[2] = 0  # ROW_HITS
        out = engine.collect_after_compact(
            engine.launch_after_compact(packed, cap=0xFFFF)
        )
        np.testing.assert_array_equal(out, np.zeros(64, dtype=np.uint32))


class TestPerDeviceCostScaling:
    def test_compact_per_device_cost_scales_inverse_n(self, mesh):
        """The honest scaling evidence a serialized virtual mesh can give:
        the compact per-shard program's COMPILED cost (XLA cost_analysis)
        must be ~1/N of the single-device program at the same total batch
        with balanced routing — on concurrent real chips that per-chip
        work reduction IS the throughput scaling, modulo routing and
        collectives. (Wall clock cannot show it here: 8 virtual devices
        share one core.)"""
        import functools

        import jax.numpy as jnp

        from api_ratelimit_tpu.ops.slab import make_slab, slab_step_after
        from api_ratelimit_tpu.parallel.sharded_slab import (
            sharded_slab_step_after_compact,
        )

        n_dev, batch, slots = 8, 4096, 8 * 4096
        engine = ShardedSlabEngine(mesh=mesh, n_slots_global=slots, use_pallas=False)

        single = jax.jit(
            functools.partial(slab_step_after, out_dtype=jnp.uint16),
            donate_argnums=(0,),
        )
        state = jax.device_put(make_slab(slots), jax.devices()[0])
        block = jnp.zeros((7, batch), dtype=jnp.uint32)
        c1 = single.lower(state, block).compile().cost_analysis()
        c1 = c1[0] if isinstance(c1, list) else c1

        step = sharded_slab_step_after_compact(mesh, 0xFFFF, ways=128, use_pallas=False)
        blocks = jax.device_put(
            np.zeros((n_dev, 7, batch // n_dev), dtype=np.uint32),
            engine._blocks_sharding,
        )
        cN = step.lower(engine._state, blocks).compile().cost_analysis()
        cN = cN[0] if isinstance(cN, list) else cN

        f1, fN = float(c1["flops"]), float(cN["flops"])
        b1, bN = float(c1["bytes accessed"]), float(cN["bytes accessed"])
        assert f1 > 0 and b1 > 0
        # ideal 1/8 = 0.125; allow sort-log-factor + fixed overhead slack
        assert fN / f1 < 0.25, (fN, f1)
        assert bN / b1 < 0.25, (bN, b1)
