"""Multi-chip sharded slab tests on the virtual 8-device CPU mesh.

Parity contract: sharding only selects WHICH device's sub-table a key lives
in (parallel/sharded_slab.py); decisions must match both the single-device
slab and the pure-Python memory oracle exactly, the way Redis Cluster gives
the reference identical semantics to a single Redis (src/redis/
driver_impl.go:104-110).
"""

import random

import jax
import numpy as np
import pytest

from api_ratelimit_tpu.backends import MemoryRateLimitCache
from api_ratelimit_tpu.backends.tpu import TpuRateLimitCache
from api_ratelimit_tpu.limiter import BaseRateLimiter
from api_ratelimit_tpu.models import Code, Descriptor, RateLimitRequest, Unit
from api_ratelimit_tpu.models.config import RateLimit, new_rate_limit_stats
from api_ratelimit_tpu.models.response import RateLimitValue
from api_ratelimit_tpu.parallel import ShardedSlabEngine, make_mesh
from api_ratelimit_tpu.stats import Store, TestSink
from api_ratelimit_tpu.utils import FakeTimeSource


def make_limit(store, rpu, unit, key):
    return RateLimit(
        full_key=key,
        stats=new_rate_limit_stats(store, key),
        limit=RateLimitValue(requests_per_unit=rpu, unit=unit),
    )


def req(*pairs, hits=1, domain="domain"):
    return RateLimitRequest(
        domain=domain,
        descriptors=tuple(Descriptor.of(p) for p in pairs),
        hits_addend=hits,
    )


def make_sharded_cache(ts, mesh, n_slots=1 << 15):
    base = BaseRateLimiter(ts, local_cache=None, near_limit_ratio=0.8)
    return TpuRateLimitCache(
        base,
        n_slots=n_slots,
        batch_window_seconds=0.0,
        buckets=(128, 1024),
        max_batch=1024,
        use_pallas=False,
        mesh=mesh,
    )


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must force the 8-device CPU mesh"
    return make_mesh()


class TestShardedEngine:
    def test_state_spans_mesh(self, mesh):
        eng = ShardedSlabEngine(mesh=mesh, n_slots_global=8 * 256)
        assert eng._state.shape == (8 * 256, 8)
        assert len(eng._state.sharding.device_set) == 8

    def test_bad_slot_split_rejected(self, mesh):
        with pytest.raises(ValueError):
            ShardedSlabEngine(mesh=mesh, n_slots_global=8 * 300)

    def test_over_limit_sequence(self, mesh):
        ts = FakeTimeSource(1_000_000)
        store = Store(TestSink())
        cache = make_sharded_cache(ts, mesh)
        limit = make_limit(store, 3, Unit.MINUTE, "k_v")
        for want in [Code.OK, Code.OK, Code.OK, Code.OVER_LIMIT]:
            resp = cache.do_limit(req(("k", "v")), [limit])
            assert resp.descriptor_statuses[0].code == want
        cache.close()

    def test_keys_spread_and_count_independently(self, mesh):
        ts = FakeTimeSource(1_000_000)
        store = Store(TestSink())
        cache = make_sharded_cache(ts, mesh)
        limits = [make_limit(store, 5, Unit.HOUR, f"k_{i}") for i in range(64)]
        descriptors = [("k", str(i)) for i in range(64)]
        # 64 distinct keys in one batch, repeated: each counts on its own shard
        for round_no in range(6):
            resp = cache.do_limit(req(*descriptors), limits)
            want = Code.OK if round_no < 5 else Code.OVER_LIMIT
            for s in resp.descriptor_statuses:
                assert s.code == want
        cache.close()

    def test_parity_vs_memory_oracle_random_stream(self, mesh):
        rng = random.Random(7)
        ts_a, ts_b = FakeTimeSource(1_700_000_000), FakeTimeSource(1_700_000_000)
        store = Store(TestSink())
        sharded = make_sharded_cache(ts_a, mesh)
        base_b = BaseRateLimiter(ts_b, local_cache=None, near_limit_ratio=0.8)
        oracle = MemoryRateLimitCache(base_b)

        limits_a = [make_limit(store, 10, Unit.MINUTE, f"u_{i}") for i in range(20)]
        limits_b = [make_limit(store, 10, Unit.MINUTE, f"u_{i}") for i in range(20)]

        for step in range(120):
            idxs = rng.sample(range(20), k=rng.randint(1, 6))
            descriptors = [("user", str(i)) for i in idxs]
            ra = sharded.do_limit(
                req(*descriptors), [limits_a[i] for i in idxs]
            )
            rb = oracle.do_limit(
                req(*descriptors), [limits_b[i] for i in idxs]
            )
            for sa, sb in zip(ra.descriptor_statuses, rb.descriptor_statuses):
                assert (sa.code, sa.limit_remaining, sa.duration_until_reset) == (
                    sb.code,
                    sb.limit_remaining,
                    sb.duration_until_reset,
                ), f"diverged at step {step}"
            if rng.random() < 0.3:
                ts_a.advance(7)
                ts_b.advance(7)
        sharded.close()

    def test_duplicate_keys_in_one_batch_serialize(self, mesh):
        ts = FakeTimeSource(1_000_000)
        store = Store(TestSink())
        cache = make_sharded_cache(ts, mesh)
        limit1 = make_limit(store, 3, Unit.MINUTE, "dup")
        limit2 = make_limit(store, 3, Unit.MINUTE, "dup")
        # 4 hits on the same key in ONE request: 3 OK-ish then OVER
        resp = cache.do_limit(
            req(("d", "x"), ("d", "x"), ("d", "x"), ("d", "x")),
            [limit1, limit2, limit1, limit2],
        )
        codes = [s.code for s in resp.descriptor_statuses]
        assert codes == [Code.OK, Code.OK, Code.OK, Code.OVER_LIMIT]
        cache.close()

    def test_window_rollover(self, mesh):
        ts = FakeTimeSource(1_000_000)
        store = Store(TestSink())
        cache = make_sharded_cache(ts, mesh)
        limit = make_limit(store, 2, Unit.SECOND, "s")
        assert (
            cache.do_limit(req(("a", "b"), hits=2), [limit])
            .descriptor_statuses[0]
            .code
            == Code.OK
        )
        assert (
            cache.do_limit(req(("a", "b")), [limit]).descriptor_statuses[0].code
            == Code.OVER_LIMIT
        )
        ts.advance(1)  # next fixed window
        assert (
            cache.do_limit(req(("a", "b")), [limit]).descriptor_statuses[0].code
            == Code.OK
        )
        cache.close()
