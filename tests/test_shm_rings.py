"""Cross-process shm submit rings (backends/shm_ring.py): publish/redeem
round trips through the unchanged dispatch drain loop, verdict error
codes, arena exhaustion shedding, the arena-pressure telemetry satellite
(dispatch.arena_overflow / ring.arena_hwm), the SHM_RINGS=false
byte-identical rollback arm, the SIGKILL-a-frontend-mid-publish chaos
story (seqno torn-frame skip + zero failed requests for the survivors),
and the real multi-process end-to-end path against a live engine.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np
import pytest

from api_ratelimit_tpu.backends.dispatch import DispatchLoop, SubmitRing, _Ticket
from api_ratelimit_tpu.backends.overload import QueueFullError
from api_ratelimit_tpu.backends.shm_ring import (
    FAULT_SITE_PUBLISH,
    ShmControlServer,
    ShmRingClient,
    ShmRingProducer,
    ShmUnavailable,
)
from api_ratelimit_tpu.limiter.cache import CacheError, DeadlineExceededError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _block(values):
    b = np.zeros((6, len(values)), dtype=np.uint32)
    b[2] = values
    return b


def _echo_loop(**kwargs):
    def launch(blocks):
        return [np.array(b[2]) for b in blocks]

    def collect(token):
        return np.concatenate(token)

    return DispatchLoop(launch, collect, **kwargs)


@pytest.fixture
def shm_stack():
    """(loop, control server, client) over fake echo executors, torn
    down in order (client -> server -> loop) so segments unlink."""
    loop = _echo_loop(window_seconds=0.002)
    td = tempfile.mkdtemp()
    path = os.path.join(td, "ctl.sock")
    server = ShmControlServer(loop, path)
    client = ShmRingClient(path, arena_rows=256)
    yield loop, server, client, path
    client.close()
    server.close()
    loop.close()


class TestShmRoundTrip:
    def test_single_frame(self, shm_stack):
        _loop, _srv, client, _path = shm_stack
        assert client.submit(_block([7, 8, 9])).tolist() == [7, 8, 9]

    def test_many_frames_wrap_slots_and_arena(self, shm_stack):
        """Far more frames than slots and rows than the arena: the
        cursor wraps and every verdict still lands on its own frame."""
        _loop, _srv, client, _path = shm_stack
        for i in range(300):
            vals = [i * 7 + j for j in range(1 + i % 5)]
            assert client.submit(_block(vals)).tolist() == vals

    def test_threads_get_their_own_rings(self, shm_stack):
        loop, _srv, client, _path = shm_stack
        results: dict = {}

        def worker(tid):
            for _ in range(30):
                results[tid] = client.submit(
                    _block([tid * 100, tid * 100 + 1])
                ).tolist()

        threads = [
            threading.Thread(target=worker, args=(t,)) for t in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(20.0)
        assert results == {
            t: [t * 100, t * 100 + 1] for t in range(4)
        }
        # one shm ring per frontend thread, all on the one loop
        assert len(loop._ext_rings) == 4

    def test_mixed_with_in_process_rings(self, shm_stack):
        """shm frames and the owner process's own in-process submits
        coalesce through the same drain loop."""
        loop, _srv, client, _path = shm_stack
        assert client.submit(_block([5])).tolist() == [5]
        assert loop.submit(_block([6])).tolist() == [6]
        assert client.submit(_block([7])).tolist() == [7]

    def test_owner_launch_error_maps_to_cache_error(self):
        calls = []

        def launch(blocks):
            calls.append(1)
            if len(calls) == 1:
                raise CacheError("device on fire")
            return [np.array(b[2]) for b in blocks]

        loop = DispatchLoop(launch, lambda token: np.concatenate(token))
        td = tempfile.mkdtemp()
        path = os.path.join(td, "ctl.sock")
        server = ShmControlServer(loop, path)
        client = ShmRingClient(path, arena_rows=64)
        try:
            with pytest.raises(CacheError):
                client.submit(_block([1]))
            assert client.submit(_block([2])).tolist() == [2]
        finally:
            client.close()
            server.close()
            loop.close()

    def test_expired_deadline_dropped_at_take(self):
        """A frame whose propagated deadline lapses in the ring comes
        back as DeadlineExceededError — same take-time drop as the
        in-process arm, now across the process boundary."""
        from api_ratelimit_tpu.utils.deadline import deadline_scope

        gate = threading.Event()
        launched = []

        def launch(blocks):
            launched.extend(int(b[2][0]) for b in blocks)
            return [np.array(b[2]) for b in blocks]

        def collect(token):
            gate.wait(5.0)
            return np.concatenate(token)

        loop = DispatchLoop(launch, collect)
        td = tempfile.mkdtemp()
        path = os.path.join(td, "ctl.sock")
        server = ShmControlServer(loop, path)
        client = ShmRingClient(path, arena_rows=64)
        try:
            # occupy the owner with a gated readback
            t1 = threading.Thread(target=lambda: loop.submit(_block([1])))
            t1.start()
            deadline = time.monotonic() + 2.0
            while not launched and time.monotonic() < deadline:
                time.sleep(0.005)
            errors = []

            def expiring():
                with deadline_scope(0.05):
                    try:
                        client.submit(_block([99]))
                    except DeadlineExceededError as e:
                        errors.append(e)

            t2 = threading.Thread(target=expiring)
            t2.start()
            time.sleep(0.15)
            gate.set()
            t1.join(5.0)
            t2.join(5.0)
            assert len(errors) == 1
            assert 99 not in launched
        finally:
            client.close()
            server.close()
            loop.close()

    def test_oversized_frame_sheds_queue_full(self, shm_stack):
        _loop, _srv, client, _path = shm_stack
        with pytest.raises(QueueFullError):
            client.submit(_block(list(range(300))))  # arena_rows=256
        # the ring survives the shed
        assert client.submit(_block([1])).tolist() == [1]

    def test_dead_owner_raises_shm_unavailable(self):
        loop = _echo_loop()
        td = tempfile.mkdtemp()
        path = os.path.join(td, "ctl.sock")
        server = ShmControlServer(loop, path)
        client = ShmRingClient(path, arena_rows=64)
        try:
            assert client.submit(_block([1])).tolist() == [1]
            server.close()
            loop.close()
            time.sleep(0.1)
            with pytest.raises(ShmUnavailable):
                client.submit(_block([2]))
            assert client.dead
        finally:
            client.close()


class TestArenaPressureTelemetry:
    def test_in_process_owned_copy_counted(self):
        """The in-process ring's owned-copy fallback is no longer
        silent: overflow_count and the arena high-water mark move."""
        ring = SubmitRing(slots=64, arena_rows=4)
        ticket = _Ticket()
        src = _block([7, 8, 9])
        ring.publish(src, 3, None, 0.0, ticket, False)  # arena
        assert ring.overflow_count == 0
        assert ring.arena_hwm == 3
        ring.publish(src, 3, None, 0.0, ticket, False)  # overflow copy
        assert ring.overflow_count == 1

    def test_stats_exported_via_dispatch_scope(self):
        from api_ratelimit_tpu.stats.sinks import NullSink
        from api_ratelimit_tpu.stats.store import Store

        store = Store(NullSink())
        loop = _echo_loop(
            scope=store.scope("ratelimit"), ring_rows=4, window_seconds=0.0
        )
        try:
            loop.submit(_block([1, 2, 3]))
            loop.submit(_block([4, 5, 6]))
            snap = store.debug_snapshot()
            assert "ratelimit.dispatch.arena_overflow" in snap
            assert "ratelimit.dispatch.ring.arena_hwm" in snap
            assert snap["ratelimit.dispatch.ring.arena_hwm"] >= 3
        finally:
            loop.close()

    def test_shm_overflow_visible_to_owner(self, shm_stack):
        loop, _srv, client, _path = shm_stack
        with pytest.raises(QueueFullError):
            client.submit(_block(list(range(300))))
        overflow, hwm = loop.arena_pressure()
        assert overflow >= 1


class TestByteIdenticalRollback:
    """SHM_RINGS=false must leave the PR-10 submit path untouched: no
    control socket derivation, no shm client construction, and the
    socket frames (already pinned byte-for-byte by test_sidecar) as the
    only path."""

    def test_settings_gate(self):
        from api_ratelimit_tpu.settings import Settings

        s = Settings()
        s.sidecar_socket = "/tmp/x.sock"
        assert s.shm_control_path() == "/tmp/x.sock.shmctl"
        s.shm_rings = False
        assert s.shm_control_path() == ""
        s.shm_rings = True
        s.sidecar_socket = "tcp://host:1"
        assert s.shm_control_path() == ""  # shared memory can't cross hosts
        s.shm_control_sock = "/tmp/ctl.sock"
        assert s.shm_control_path() == "/tmp/ctl.sock"

    def test_rollback_arm_builds_no_shm_and_matches_results(self, monkeypatch):
        """Same request stream through an shm-on owner/client pair and a
        rollback pair: identical verdict bytes; the rollback client must
        never even construct an ShmRingClient."""
        from api_ratelimit_tpu.backends import shm_ring as shm_mod
        from api_ratelimit_tpu.backends.sidecar import (
            SidecarEngineClient,
            SlabSidecarServer,
        )
        from api_ratelimit_tpu.backends.tpu import SlabDeviceEngine
        from api_ratelimit_tpu.utils import FakeTimeSource

        def stream():
            import random

            rng = random.Random(11)
            for _ in range(30):
                n = rng.randrange(1, 6)
                b = np.zeros((6, n), dtype=np.uint32)
                b[0] = [rng.randrange(1, 40) for _ in range(n)]
                b[2] = 1
                b[3] = rng.randrange(2, 30)
                b[4] = 60
                yield b

        results = {}
        for arm in ("shm", "rollback"):
            td = tempfile.mkdtemp()
            sock = os.path.join(td, "s.sock")
            ctl = sock + ".shmctl"
            engine = SlabDeviceEngine(
                FakeTimeSource(700_000),
                n_slots=1 << 10,
                use_pallas=False,
                buckets=(8, 128),
                batch_window_seconds=0.0005,
                max_batch=512,
                block_mode=True,
            )
            server = SlabSidecarServer(
                sock, engine, shm_control_path=ctl if arm == "shm" else ""
            )
            if arm == "rollback":
                def boom(*a, **k):
                    raise AssertionError(
                        "rollback arm constructed an ShmRingClient"
                    )

                monkeypatch.setattr(shm_mod, "ShmRingClient", boom)
            client = SidecarEngineClient(
                sock,
                shm_control_path=ctl if arm == "shm" else "",
            )
            if arm == "shm":
                assert client._shm is not None
            else:
                assert client._shm is None
            got = []
            try:
                for b in stream():
                    got.append(client.submit_rows(b).tobytes())
            finally:
                client.close()
                server.close()
            results[arm] = got
            monkeypatch.undo()
        assert results["shm"] == results["rollback"]


_KILL_CHILD = """\
import os, sys
import numpy as np
sys.path.insert(0, {repo!r})
from api_ratelimit_tpu.backends.shm_ring import ShmRingClient
from api_ratelimit_tpu.testing.faults import FaultInjector

inj = FaultInjector.from_spec("{site}:delay_ms:30000")
client = ShmRingClient({path!r}, arena_rows=64, fault_injector=inj)
b = np.zeros((6, 2), dtype=np.uint32)
b[2] = [41, 42]
print("publishing", flush=True)
client.submit(b)  # parks 30s in the torn-frame window; parent SIGKILLs
"""


class TestChaosSigkillMidPublish:
    def test_owner_skips_torn_frame_and_survivors_see_zero_failures(self):
        """SIGKILL a frontend PROCESS exactly between its arena copy and
        its seqno store (the dispatch.ring_publish fault site holds it
        there): the owner must never launch the torn frame, must detach
        the dead ring on the control socket's EOF, must unlink the
        segment, and every other frontend's requests keep succeeding."""
        launched: list[int] = []

        def launch(blocks):
            launched.extend(int(v) for b in blocks for v in b[2])
            return [np.array(b[2]) for b in blocks]

        loop = DispatchLoop(launch, lambda token: np.concatenate(token))
        td = tempfile.mkdtemp()
        path = os.path.join(td, "ctl.sock")
        server = ShmControlServer(loop, path)
        survivor = ShmRingClient(path, arena_rows=64)
        try:
            # survivor traffic before, during, and after the kill
            assert survivor.submit(_block([1])).tolist() == [1]
            child = subprocess.Popen(
                [
                    sys.executable,
                    "-c",
                    _KILL_CHILD.format(
                        repo=REPO, site=FAULT_SITE_PUBLISH, path=path
                    ),
                ],
                stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL,
                text=True,
            )
            assert child.stdout.readline().strip() == "publishing"
            time.sleep(0.4)  # child is parked inside the fault delay
            n_ext_before = len(loop._ext_rings)
            assert n_ext_before >= 2  # survivor + child rings attached
            os.kill(child.pid, signal.SIGKILL)
            child.wait(10.0)
            # control EOF -> detach; survivor unaffected throughout
            failures = 0
            deadline = time.monotonic() + 10.0
            while len(loop._ext_rings) > 1 and time.monotonic() < deadline:
                assert survivor.submit(_block([2, 3])).tolist() == [2, 3]
            assert len(loop._ext_rings) == 1, "dead ring never detached"
            for _ in range(20):
                assert survivor.submit(_block([4])).tolist() == [4]
            assert failures == 0
            # the torn frame ([41, 42]) must never have launched
            assert 41 not in launched and 42 not in launched
        finally:
            survivor.close()
            server.close()
            loop.close()
        # the dead child's segment was unlinked by the owner
        import glob

        assert not glob.glob(f"/dev/shm/rlring_{child.pid}_*")


_MP_CHILD = """\
import os, sys
import numpy as np
sys.path.insert(0, {repo!r})
from api_ratelimit_tpu.backends.shm_ring import ShmRingClient

client = ShmRingClient({path!r}, arena_rows=512)
total = 0
for i in range(200):
    b = np.zeros((6, 1), dtype=np.uint32)
    b[0] = 4242
    b[2] = 1
    b[3] = 1 << 30
    b[4] = 60
    total = int(client.submit(b)[0])
print("TOTAL", total, flush=True)
client.close()
"""


@pytest.mark.mp
class TestMultiProcessEndToEnd:
    def test_two_frontend_processes_share_one_exact_counter(self):
        """Two real frontend PROCESSES increment one key through shm
        rings into one live engine: the post-increment counters must
        partition 1..400 exactly — global exactness across processes,
        the property the whole split exists to keep."""
        from api_ratelimit_tpu.backends.tpu import SlabDeviceEngine
        from api_ratelimit_tpu.utils import FakeTimeSource

        engine = SlabDeviceEngine(
            FakeTimeSource(700_000),
            n_slots=1 << 10,
            use_pallas=False,
            buckets=(8, 128),
            batch_window_seconds=0.0005,
            max_batch=512,
        )
        td = tempfile.mkdtemp()
        path = os.path.join(td, "ctl.sock")
        server = ShmControlServer(engine.dispatch_loop, path)
        procs = []
        try:
            for _ in range(2):
                procs.append(
                    subprocess.Popen(
                        [
                            sys.executable,
                            "-c",
                            _MP_CHILD.format(repo=REPO, path=path),
                        ],
                        stdout=subprocess.PIPE,
                        stderr=subprocess.DEVNULL,
                        text=True,
                    )
                )
            totals = []
            for proc in procs:
                out, _ = proc.communicate(timeout=120)
                assert proc.returncode == 0, out
                totals.append(int(out.split()[-1]))
            # each child's LAST counter: the max must be exactly 400
            # (200 + 200 increments, no loss, no double count)
            assert max(totals) == 400, totals
        finally:
            for proc in procs:
                if proc.poll() is None:
                    proc.kill()
            server.close()
            engine.close()


@pytest.mark.mp
@pytest.mark.slow
class TestFrontendProcessFleet:
    def test_service_cmd_fleet_serves_and_tears_down(self, tmp_path):
        """FRONTEND_PROCS=2 through the real entry point: the master
        spawns a device owner + two frontend worker processes sharing
        one HTTP port (SO_REUSEPORT); /json answers from the shared
        slab (counters exact across workers via the one owner), and
        SIGTERM tears the fleet down cleanly."""
        import json
        import socket
        import urllib.error
        import urllib.request

        def free_port():
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
            return port

        http_port, grpc_port, debug_port = (
            free_port(),
            free_port(),
            free_port(),
        )
        env = dict(os.environ)
        env.update(
            {
                "FRONTEND_PROCS": "2",
                "BACKEND_TYPE": "tpu",
                "JAX_PLATFORMS": "cpu",
                "RUNTIME_ROOT": os.path.join(REPO, "examples", "ratelimit"),
                "RUNTIME_SUBDIRECTORY": "",
                "RUNTIME_WATCH_ROOT": "false",
                "USE_STATSD": "false",
                "LOG_LEVEL": "WARN",
                "PORT": str(http_port),
                "GRPC_PORT": str(grpc_port),
                "DEBUG_PORT": str(debug_port),
                "SIDECAR_SOCKET": str(tmp_path / "owner.sock"),
                "TPU_BATCH_WINDOW": "0.0005",
                "TPU_SLAB_SLOTS": str(1 << 12),
                "TPU_BUCKETS": "8,128",
                "TPU_PRECOMPILE": "false",
            }
        )
        env.pop("XLA_FLAGS", None)
        master = subprocess.Popen(
            [sys.executable, "-m", "api_ratelimit_tpu.cmd.service_cmd"],
            env=env,
        )
        url = f"http://localhost:{http_port}/json"
        body = json.dumps(
            {
                "domain": "mongo_cps",
                "descriptors": [
                    {"entries": [{"key": "database", "value": "users"}]}
                ],
            }
        ).encode()

        def post():
            req = urllib.request.Request(
                url,
                data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=10) as r:
                return r.status, json.loads(r.read().decode())

        try:
            deadline = time.monotonic() + 240.0
            last_err = None
            while True:
                try:
                    status, out = post()
                    break
                except (urllib.error.URLError, ConnectionError, OSError) as e:
                    last_err = e
                    assert master.poll() is None, "fleet master died"
                    assert time.monotonic() < deadline, f"fleet never served: {last_err}"
                    time.sleep(0.5)
            assert status == 200
            assert out["overallCode"] == "OK"
            # a burst across the shared port: every answer OK, the fleet
            # stays alive (whichever worker the kernel picks, the slab
            # behind them is the one device owner)
            for _ in range(30):
                status, out = post()
                assert status == 200, out
                assert out["overallCode"] == "OK"
            assert master.poll() is None
        finally:
            master.terminate()
            try:
                master.wait(30.0)
            except subprocess.TimeoutExpired:
                master.kill()
                master.wait()
        assert master.returncode is not None
