"""Slab sidecar tests: protocol round trip, global counting across many
frontends (the reason the sidecar exists), differential parity vs the
memory oracle, and failure surfacing (backends/sidecar.py)."""

from __future__ import annotations

import threading

import pytest

from api_ratelimit_tpu.backends.memory import MemoryRateLimitCache
from api_ratelimit_tpu.backends.sidecar import (
    SidecarEngineClient,
    SlabSidecarServer,
    decode_items,
    encode_items,
)
from api_ratelimit_tpu.backends.tpu import SlabDeviceEngine, TpuRateLimitCache, _Item
from api_ratelimit_tpu.limiter.base_limiter import BaseRateLimiter
from api_ratelimit_tpu.limiter.cache import CacheError
from api_ratelimit_tpu.models import Code, Descriptor, RateLimitRequest, Unit
from api_ratelimit_tpu.models.config import RateLimit, new_rate_limit_stats
from api_ratelimit_tpu.models.response import RateLimitValue
from api_ratelimit_tpu.stats import Store, TestSink
from api_ratelimit_tpu.utils import FakeTimeSource


def make_limit(store, rpu, unit, key):
    return RateLimit(
        full_key=key,
        stats=new_rate_limit_stats(store, key),
        limit=RateLimitValue(requests_per_unit=rpu, unit=unit),
    )


def req(*pairs, hits=1, domain="domain"):
    return RateLimitRequest(
        domain=domain,
        descriptors=tuple(Descriptor.of(p) for p in pairs),
        hits_addend=hits,
    )


def _make_engine(ts):
    return SlabDeviceEngine(
        time_source=ts,
        n_slots=1 << 12,
        buckets=(128, 1024),
        max_batch=1024,
        use_pallas=False,
        block_mode=True,  # the production sidecar server runs block-native
    )


@pytest.fixture(params=["unix", "tcp"])
def sidecar(request, tmp_path):
    """A running sidecar (CPU engine, deterministic clock) + its address.
    Parametrized over the unix-socket and TCP transports so the whole
    end-to-end matrix certifies both (TLS has its own dedicated test)."""
    ts = FakeTimeSource(1_000_000)
    engine = _make_engine(ts)
    if request.param == "unix":
        address = str(tmp_path / "slab.sock")
        server = SlabSidecarServer(address, engine)
    else:
        server = SlabSidecarServer("tcp://127.0.0.1:0", engine)
        address = f"tcp://127.0.0.1:{server.port}"
    yield address, ts
    server.close()


def frontend(path, ts, local_cache_size=0):
    base = BaseRateLimiter(ts, near_limit_ratio=0.8)
    return TpuRateLimitCache(base, engine=SidecarEngineClient(path))


class TestCodec:
    def test_items_roundtrip(self):
        items = [
            _Item(fp=0xDEADBEEFCAFEF00D, hits=2, limit=100, divider=60, jitter=5),
            _Item(fp=1, hits=1, limit=7, divider=1, jitter=0),
            _Item(fp=2**64 - 1, hits=3, limit=2**32 - 2, divider=86400, jitter=299),
        ]
        assert decode_items(encode_items(items)) == items

    def test_empty_batch(self):
        assert decode_items(encode_items([])) == []


class TestSidecarEndToEnd:
    def test_basic_over_limit_sequence(self, sidecar, test_store):
        path, ts = sidecar
        store, _ = test_store
        cache = frontend(path, ts)
        limit = make_limit(store.scope("t"), 3, Unit.MINUTE, "k_v")
        for want in [Code.OK, Code.OK, Code.OK, Code.OVER_LIMIT]:
            resp = cache.do_limit(req(("k", "v")), [limit])
            assert resp.descriptor_statuses[0].code == want
        cache.close()

    def test_global_counts_across_frontends(self, sidecar, test_store):
        """THE sidecar property: N frontend processes, one slab — limits are
        globally exact, like N reference replicas against one Redis."""
        path, ts = sidecar
        store, _ = test_store
        frontends = [frontend(path, ts) for _ in range(4)]
        limit = make_limit(store.scope("t"), 1_000_000, Unit.HOUR, "g")
        remaining: list[int] = []
        lock = threading.Lock()

        def worker(cache, k):
            local = []
            for _ in range(25):
                resp = cache.do_limit(req(("g", "shared")), [limit])
                local.append(resp.descriptor_statuses[0].limit_remaining)
            with lock:
                remaining.extend(local)

        threads = [
            threading.Thread(target=worker, args=(c, i))
            for i, c in enumerate(frontends)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(20.0)
        for c in frontends:
            c.close()
        # 100 hits on one key through 4 frontends: every decision saw a
        # distinct counter value => exact global serialization
        assert len(remaining) == 100
        assert len(set(remaining)) == 100
        assert min(remaining) == 1_000_000 - 100

    def test_differential_vs_memory_oracle(self, sidecar, test_store):
        path, ts = sidecar
        store, _ = test_store
        import random

        rng = random.Random(5)
        ts_oracle = FakeTimeSource(1_000_000)
        cache = frontend(path, ts)
        oracle = MemoryRateLimitCache(
            BaseRateLimiter(ts_oracle, near_limit_ratio=0.8)
        )
        scope = store.scope("t")
        limits_a = {}
        limits_b = {}
        for i in range(8):
            unit = [Unit.SECOND, Unit.MINUTE, Unit.HOUR][i % 3]
            rpu = rng.randrange(2, 10)
            limits_a[i] = make_limit(scope, rpu, unit, f"a{i}")
            limits_b[i] = make_limit(scope, rpu, unit, f"b{i}")
        for step in range(150):
            if rng.random() < 0.25:
                ts.advance(1)
                ts_oracle.advance(1)
            k = rng.randrange(8)
            request = req(("api", str(k)), hits=rng.randrange(1, 3))
            ra = cache.do_limit(request, [limits_a[k]])
            rb = oracle.do_limit(request, [limits_b[k]])
            sa, sb = ra.descriptor_statuses[0], rb.descriptor_statuses[0]
            assert (sa.code, sa.limit_remaining) == (sb.code, sb.limit_remaining), step
        cache.close()

    def test_server_down_surfaces_cache_error(self, tmp_path):
        with pytest.raises(CacheError, match="cannot reach slab sidecar"):
            SidecarEngineClient(str(tmp_path / "nope.sock"))

    def test_tcp_server_down_surfaces_cache_error(self):
        with pytest.raises(CacheError, match="cannot reach slab sidecar"):
            SidecarEngineClient("tcp://127.0.0.1:1")


class TestAddressParsing:
    def test_schemes(self):
        from api_ratelimit_tpu.backends.sidecar import parse_sidecar_address

        assert parse_sidecar_address("/tmp/x.sock") == ("unix", "/tmp/x.sock")
        assert parse_sidecar_address("tcp://h:123") == ("tcp", ("h", 123))
        assert parse_sidecar_address("tls://10.0.0.2:9") == (
            "tls",
            ("10.0.0.2", 9),
        )
        assert parse_sidecar_address("tcp://:80") == ("tcp", ("127.0.0.1", 80))
        with pytest.raises(ValueError):
            parse_sidecar_address("tcp://nohost")
        with pytest.raises(ValueError):
            parse_sidecar_address("tls://h:notaport")


class TestTlsTransport:
    """tls:// — the cross-host DCN transport with mutual TLS, mirroring the
    reference's REDIS_TLS + auth dial options (driver_impl.go:60-78)."""

    @pytest.fixture
    def tls_material(self, tmp_path):
        import shutil
        import subprocess

        if shutil.which("openssl") is None:
            pytest.skip("openssl binary not available")
        ca_key, ca_crt = tmp_path / "ca.key", tmp_path / "ca.crt"
        srv_key, srv_csr, srv_crt = (
            tmp_path / "s.key",
            tmp_path / "s.csr",
            tmp_path / "s.crt",
        )
        cli_key, cli_csr, cli_crt = (
            tmp_path / "c.key",
            tmp_path / "c.csr",
            tmp_path / "c.crt",
        )

        def run(*args, stdin: bytes | None = None):
            subprocess.run(args, input=stdin, check=True, capture_output=True)

        run(
            "openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
            "-keyout", str(ca_key), "-out", str(ca_crt), "-days", "1",
            "-subj", "/CN=test-ca",
        )
        for key, csr, crt, cn, san in (
            (srv_key, srv_csr, srv_crt, "localhost",
             b"subjectAltName=DNS:localhost,IP:127.0.0.1"),
            (cli_key, cli_csr, cli_crt, "frontend", None),
        ):
            run(
                "openssl", "req", "-newkey", "rsa:2048", "-nodes",
                "-keyout", str(key), "-out", str(csr), "-subj", f"/CN={cn}",
            )
            sign = [
                "openssl", "x509", "-req", "-in", str(csr), "-CA", str(ca_crt),
                "-CAkey", str(ca_key), "-CAcreateserial", "-days", "1",
                "-out", str(crt),
            ]
            if san:
                sign += ["-extfile", "/dev/stdin"]
            run(*sign, stdin=san)
        return {
            "ca": str(ca_crt),
            "srv_crt": str(srv_crt),
            "srv_key": str(srv_key),
            "cli_crt": str(cli_crt),
            "cli_key": str(cli_key),
        }

    def test_mutual_tls_end_to_end(self, tls_material, test_store):
        ts = FakeTimeSource(1_000_000)
        server = SlabSidecarServer(
            "tls://127.0.0.1:0",
            _make_engine(ts),
            tls_cert=tls_material["srv_crt"],
            tls_key=tls_material["srv_key"],
            tls_ca=tls_material["ca"],  # require client certs
        )
        try:
            store, _ = test_store
            base = BaseRateLimiter(ts, near_limit_ratio=0.8)
            cache = TpuRateLimitCache(
                base,
                engine=SidecarEngineClient(
                    f"tls://127.0.0.1:{server.port}",
                    tls_ca=tls_material["ca"],
                    tls_cert=tls_material["cli_crt"],
                    tls_key=tls_material["cli_key"],
                    tls_server_name="localhost",
                ),
            )
            limit = make_limit(store.scope("t"), 3, Unit.MINUTE, "k_v")
            for want in [Code.OK, Code.OK, Code.OK, Code.OVER_LIMIT]:
                resp = cache.do_limit(req(("k", "v")), [limit])
                assert resp.descriptor_statuses[0].code == want
            cache.close()
        finally:
            server.close()

    def test_client_without_cert_rejected(self, tls_material):
        ts = FakeTimeSource(1_000_000)
        server = SlabSidecarServer(
            "tls://127.0.0.1:0",
            _make_engine(ts),
            tls_cert=tls_material["srv_crt"],
            tls_key=tls_material["srv_key"],
            tls_ca=tls_material["ca"],  # mutual TLS required
        )
        try:
            with pytest.raises(CacheError):
                SidecarEngineClient(
                    f"tls://127.0.0.1:{server.port}",
                    tls_ca=tls_material["ca"],
                    tls_server_name="localhost",
                )
        finally:
            server.close()

    def test_server_requires_cert_material(self):
        ts = FakeTimeSource(1_000_000)
        with pytest.raises(ValueError, match="requires tls_cert"):
            SlabSidecarServer("tls://127.0.0.1:0", _make_engine(ts))

    def test_engine_failure_propagates_message(self, sidecar, test_store, tmp_path):
        path, ts = sidecar
        store, _ = test_store

        class BoomEngine:
            def submit(self, items):
                raise RuntimeError("device on fire")

            def close(self):
                pass

        boom_path = str(tmp_path / "boom.sock")
        boom = SlabSidecarServer(boom_path, BoomEngine())
        try:
            cache = frontend(boom_path, ts)
            limit = make_limit(store.scope("t"), 3, Unit.MINUTE, "k")
            with pytest.raises(CacheError, match="device on fire"):
                cache.do_limit(req(("k", "v")), [limit])
            cache.close()
        finally:
            boom.close()

    def test_connection_survives_engine_error(self, sidecar, test_store):
        """An engine error must not poison the connection for later calls."""
        path, ts = sidecar
        store, _ = test_store
        cache = frontend(path, ts)
        limit = make_limit(store.scope("t"), 5, Unit.MINUTE, "k")
        resp = cache.do_limit(req(("k", "v")), [limit])
        assert resp.descriptor_statuses[0].code == Code.OK
        # hits=0 is invalid at the protocol level but service-level hits
        # are clamped to >=1 upstream; just verify a second call works
        resp = cache.do_limit(req(("k", "v")), [limit])
        assert resp.descriptor_statuses[0].code == Code.OK
        cache.close()


class TestRunnerIntegration:
    def test_backend_type_tpu_sidecar(self, tmp_path, test_store):
        """Full runner with BACKEND_TYPE=tpu-sidecar against an in-process
        sidecar, driven over real gRPC."""
        import grpc

        from api_ratelimit_tpu.pb import rls_grpc, rls_v3
        from api_ratelimit_tpu.runner import Runner
        from api_ratelimit_tpu.settings import Settings
        from api_ratelimit_tpu.utils.timeutil import RealTimeSource

        engine = SlabDeviceEngine(
            time_source=RealTimeSource(),
            n_slots=1 << 12,
            buckets=(128, 1024),
            max_batch=1024,
            use_pallas=False,
            block_mode=True,
        )
        sock = str(tmp_path / "slab.sock")
        server = SlabSidecarServer(sock, engine)

        config_dir = tmp_path / "current" / "rl" / "config"
        config_dir.mkdir(parents=True)
        (config_dir / "b.yaml").write_text(
            "domain: sc\n"
            "descriptors:\n"
            "  - key: one\n"
            "    rate_limit: {unit: minute, requests_per_unit: 1}\n"
        )
        settings = Settings(
            port=0,
            grpc_port=0,
            debug_port=0,
            use_statsd=False,
            runtime_path=str(tmp_path / "current"),
            runtime_subdirectory="rl",
            backend_type="tpu-sidecar",
            sidecar_socket=sock,
            expiration_jitter_max_seconds=0,
            log_level="ERROR",
        )
        runner = Runner(settings, sink=TestSink())
        runner.run_background()
        assert runner.wait_ready(10.0)
        try:
            with grpc.insecure_channel(
                f"localhost:{runner.server.grpc_port}"
            ) as ch:
                stub = rls_grpc.RateLimitServiceV3Stub(ch)
                request = rls_v3.RateLimitRequest(domain="sc")
                d = request.descriptors.add()
                d.entries.add(key="one", value="x")
                codes = [
                    stub.ShouldRateLimit(request).overall_code for _ in range(3)
                ]
            assert codes == [
                rls_v3.RateLimitResponse.OK,
                rls_v3.RateLimitResponse.OVER_LIMIT,
                rls_v3.RateLimitResponse.OVER_LIMIT,
            ]
        finally:
            runner.stop()
            server.close()


def test_malformed_frames_never_kill_the_server(test_store):
    """A network-exposed listener must shrug off garbage: random bytes,
    truncated frames, wrong magic — each bad connection dies alone and a
    well-formed client keeps working afterward."""
    import random as random_mod
    import socket as socket_mod

    ts = FakeTimeSource(1_000_000)
    server = SlabSidecarServer("tcp://127.0.0.1:0", _make_engine(ts))
    addr = ("127.0.0.1", server.port)
    rng = random_mod.Random(0xBAD)
    try:
        for i in range(20):
            conn = socket_mod.create_connection(addr, timeout=5)
            blob = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 64)))
            try:
                conn.sendall(blob)
                conn.settimeout(2)
                conn.recv(64)  # error reply or server-side close; both fine
            except OSError:
                pass
            finally:
                conn.close()
        # the server must still serve a real frontend
        store, _ = test_store
        cache = frontend(f"tcp://127.0.0.1:{server.port}", ts)
        limit = make_limit(store.scope("t"), 3, Unit.MINUTE, "k_v")
        resp = cache.do_limit(req(("k", "v")), [limit])
        assert resp.descriptor_statuses[0].code == Code.OK
        cache.close()
    finally:
        server.close()


def test_oversized_submit_rejected_before_buffering(tmp_path):
    """A hostile/corrupt u32 count must be refused without allocating."""
    import os
    import socket as socket_mod
    import struct

    from api_ratelimit_tpu.backends import sidecar as sc

    class _NoopEngine:
        def submit(self, items):
            return [0] * len(items)

        def close(self):
            pass

    path = str(tmp_path / "slab.sock")
    server = sc.SlabSidecarServer(path, _NoopEngine())
    try:
        # socket is owner-only
        assert (os.stat(path).st_mode & 0o777) == 0o600

        conn = socket_mod.socket(socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
        conn.settimeout(5)
        conn.connect(path)
        conn.sendall(
            sc._HDR.pack(sc.MAGIC, sc.VERSION, sc.OP_SUBMIT, 0)
            + struct.pack("<I", 0xFFFFFFFF)
        )
        status = conn.recv(1)
        assert status == b"\x01"
        (ln,) = struct.unpack("<I", sc._recv_exact(conn, 4))
        message = sc._recv_exact(conn, ln).decode()
        assert "exceeds cap" in message
        conn.close()
    finally:
        server.close()


class TestSidecarRestart:
    """Owner-process restart recovery: pooled frontend connections go
    stale when the sidecar restarts; at most the in-flight/stale request
    fails (CacheError, counted upstream like any backend failure — a
    blind retry could double-count the increment), and the NEXT request
    must transparently reconnect. Reference analog: a bounced redis with
    pooled connections (driver_impl.go pool semantics)."""

    def test_frontend_recovers_after_server_restart(self, test_store):
        from api_ratelimit_tpu.limiter.cache import CacheError

        ts = FakeTimeSource(1_000_000)
        engine = _make_engine(ts)
        server = SlabSidecarServer("tcp://127.0.0.1:0", engine)
        address = f"tcp://127.0.0.1:{server.port}"
        client = SidecarEngineClient(address)
        from api_ratelimit_tpu.backends.tpu import _Item

        item = [_Item(fp=7, hits=1, limit=100, divider=60, jitter=0)]
        assert client.submit(item) == [1]

        port = server.port
        server.close()
        # restart on the SAME port with fresh (empty-slab) state
        engine2 = _make_engine(ts)
        server2 = SlabSidecarServer(f"tcp://127.0.0.1:{port}", engine2)
        try:
            # stale pooled connections each fail one request (allowed:
            # exactly-once cannot be guaranteed for a non-idempotent
            # increment, and how many conns sat pooled is incidental);
            # the client must become healthy again WITHOUT being rebuilt,
            # within pool-depth attempts
            last = None
            failures = 0
            for _ in range(10):
                try:
                    last = client.submit(item)[0]
                    break
                except CacheError:
                    failures += 1
            assert last is not None, f"never recovered ({failures} failures)"
            assert failures <= 8, failures  # bounded by pool depth
            # counters continue on the fresh slab (soft state: restart =
            # refilled windows, SURVEY.md 5.4)
            assert last >= 1
        finally:
            client.close()
            server2.close()
