"""Slab engine tests: probe/update semantics + differential parity between the
device decision math and the scalar host oracle (base_limiter)."""

import random

import jax.numpy as jnp
import numpy as np
import pytest

from api_ratelimit_tpu.ops.decide import decide
from api_ratelimit_tpu.ops.slab import (
    SlabBatch,
    make_slab,
    slab_update_and_decide,
)

N_SLOTS = 1 << 12


def make_batch(items, pad_to=None):
    """items: list of (fp, hits, limit, divider[, jitter])."""
    b = len(items)
    size = pad_to or b
    fp = np.zeros(size, dtype=np.uint64)
    hits = np.zeros(size, dtype=np.uint32)
    limit = np.zeros(size, dtype=np.uint32)
    divider = np.ones(size, dtype=np.int32)
    jitter = np.zeros(size, dtype=np.int32)
    for i, item in enumerate(items):
        f, h, l, d = item[:4]
        fp[i], hits[i], limit[i], divider[i] = f, h, l, d
        if len(item) > 4:
            jitter[i] = item[4]
    return SlabBatch(
        fp_lo=jnp.asarray((fp & 0xFFFFFFFF).astype(np.uint32)),
        fp_hi=jnp.asarray((fp >> 32).astype(np.uint32)),
        hits=jnp.asarray(hits),
        limit=jnp.asarray(limit),
        divider=jnp.asarray(divider),
        jitter=jnp.asarray(jitter),
    )


def run(state, items, now, pad_to=None, near_ratio=0.8, ways=128):
    state, res = slab_update_and_decide(
        state,
        make_batch(items, pad_to),
        jnp.int32(now),
        jnp.float32(near_ratio),
        ways=ways,
    )
    return state, res


KEY_A = 0xDEADBEEFCAFEF00D
KEY_B = 0x1234567890ABCDEF


class TestSlabBasics:
    def test_increment_and_over_limit(self):
        state = make_slab(N_SLOTS)
        # limit 3/second at now=1000
        for i, want_code in enumerate([1, 1, 1, 2, 2]):
            state, res = run(state, [(KEY_A, 1, 3, 1)], now=1000)
            assert int(res.after[0]) == i + 1
            assert int(res.decision.code[0]) == want_code

    def test_window_rollover_resets(self):
        state = make_slab(N_SLOTS)
        state, res = run(state, [(KEY_A, 3, 3, 1)], now=1000)
        assert int(res.decision.code[0]) == 1  # 3 <= 3: still OK
        state, res = run(state, [(KEY_A, 1, 3, 1)], now=1000)
        assert int(res.decision.code[0]) == 2
        state, res = run(state, [(KEY_A, 1, 3, 1)], now=1001)  # next window
        assert int(res.decision.code[0]) == 1
        assert int(res.before[0]) == 0 and int(res.after[0]) == 1

    def test_distinct_keys_do_not_share_counters(self):
        state = make_slab(N_SLOTS)
        state, res = run(state, [(KEY_A, 5, 10, 60)], now=1000)
        state, res = run(state, [(KEY_B, 1, 10, 60)], now=1000)
        assert int(res.before[0]) == 0
        assert int(res.after[0]) == 1

    def test_duplicates_in_one_batch_serialize(self):
        state = make_slab(N_SLOTS)
        items = [(KEY_A, 2, 5, 60), (KEY_B, 1, 5, 60), (KEY_A, 3, 5, 60)]
        state, res = run(state, items, now=1000)
        # KEY_A first sees before=0/after=2, second sees before=2/after=5.
        assert [int(x) for x in res.before] == [0, 0, 2]
        assert [int(x) for x in res.after] == [2, 1, 5]
        # A later batch sees the settled count.
        state, res = run(state, [(KEY_A, 1, 5, 60)], now=1000)
        assert int(res.before[0]) == 5
        assert int(res.decision.code[0]) == 2

    def test_padding_items_are_inert(self):
        state = make_slab(N_SLOTS)
        state, res = run(state, [(KEY_A, 1, 5, 60)], now=1000, pad_to=8)
        assert int(res.after[0]) == 1
        assert [int(c) for c in res.decision.code] == [1] * 8
        assert int(res.decision.near_delta.sum()) == 0
        # padding wrote nothing: a fresh key still starts at 0
        state, res = run(state, [(KEY_B, 1, 5, 60)], now=1000)
        assert int(res.before[0]) == 0

    def test_expired_slot_reused_by_new_key(self):
        state = make_slab(N_SLOTS)
        state, _ = run(state, [(KEY_A, 1, 5, 1)], now=1000)
        # KEY_A's slot expires after its 1s window (+0 jitter)
        state, res = run(state, [(KEY_B, 1, 5, 60)], now=2000)
        assert int(res.before[0]) == 0
        # KEY_A comes back later: fresh counter (old entry was reclaimed or stale)
        state, res = run(state, [(KEY_A, 1, 5, 1)], now=2000)
        assert int(res.before[0]) == 0

    def test_same_slot_distinct_keys_in_one_batch(self):
        # Two DIFFERENT keys whose first probe candidate coincides must not
        # merge into one counter: each decides on its own hits; one of them
        # wins the slot (the loser's count is not persisted — fails open).
        state = make_slab(N_SLOTS)
        k1 = 5  # fp_lo=5, fp_hi=0
        k2 = 5 + (N_SLOTS << 32)  # same candidate-0 slot, different fp_hi
        state, res = run(state, [(k1, 3, 4, 60), (k2, 2, 4, 60)], now=1000)
        assert [int(x) for x in res.before] == [0, 0]
        assert [int(x) for x in res.after] == [3, 2]
        assert [int(c) for c in res.decision.code] == [1, 1]
        # next batch: both keys again; whichever lost the slot re-probes and
        # may restart from 0, but neither may see the other's count.
        state, res = run(state, [(k1, 1, 100, 60), (k2, 1, 100, 60)], now=1000)
        assert int(res.before[0]) in (0, 3)
        assert int(res.before[1]) in (0, 2)

    def test_dual_window_same_descriptor(self):
        # per-second + per-hour limits on the same descriptor path must use
        # distinct slab entries (divider is part of the fingerprint upstream;
        # here we emulate with distinct fps).
        state = make_slab(N_SLOTS)
        sec_key, hour_key = KEY_A, KEY_A ^ 0x1
        state, res = run(
            state, [(sec_key, 1, 2, 1), (hour_key, 1, 100, 3600)], now=1000
        )
        assert [int(x) for x in res.after] == [1, 1]
        state, res = run(
            state, [(sec_key, 1, 2, 1), (hour_key, 1, 100, 3600)], now=1001
        )
        # second window rolled; hour window did not
        assert [int(x) for x in res.after] == [1, 2]


class TestDecideParityWithOracle:
    """decide() must agree with the scalar BaseRateLimiter math on every
    branch: randomized differential test."""

    def test_randomized_parity(self):
        from api_ratelimit_tpu.limiter.base_limiter import BaseRateLimiter, LimitInfo
        from api_ratelimit_tpu.models.config import RateLimit, new_rate_limit_stats
        from api_ratelimit_tpu.models.response import DoLimitResponse, RateLimitValue
        from api_ratelimit_tpu.models.units import Unit
        from api_ratelimit_tpu.stats import Store, TestSink
        from api_ratelimit_tpu.utils import FakeTimeSource

        rng = random.Random(42)
        unit_by_div = {1: Unit.SECOND, 60: Unit.MINUTE, 3600: Unit.HOUR, 86400: Unit.DAY}
        cases = []
        for _ in range(500):
            divider = rng.choice([1, 60, 3600, 86400])
            limit = rng.randrange(1, 50)
            hits = rng.randrange(1, 8)
            before = rng.randrange(0, limit + 10)
            now = rng.randrange(1, 2_000_000)
            cases.append((before, before + hits, hits, limit, divider, now))

        store = Store(TestSink())
        for i, (before, after, hits, limit, divider, now) in enumerate(cases):
            res = decide(
                jnp.uint32(before),
                jnp.uint32(after),
                jnp.uint32(hits),
                jnp.uint32(limit),
                jnp.int32(divider),
                jnp.int32(now),
                jnp.float32(0.8),
            )

            ts = FakeTimeSource(now)
            rl = RateLimit(
                full_key=f"case{i}",
                stats=new_rate_limit_stats(store, f"case{i}"),
                limit=RateLimitValue(limit, unit_by_div[divider]),
            )
            base = BaseRateLimiter(ts, near_limit_ratio=0.8)
            info = LimitInfo(rl, before, after)
            resp = DoLimitResponse()
            status = base.get_response_descriptor_status("key", info, False, hits, resp)

            ctx = f"case {i}: before={before} after={after} hits={hits} limit={limit} div={divider} now={now}"
            assert int(res.code) == int(status.code), ctx
            assert int(res.limit_remaining) == status.limit_remaining, ctx
            assert int(res.duration_until_reset) == status.duration_until_reset, ctx
            assert int(res.throttle_millis) == resp.throttle_millis, ctx
            assert int(res.near_delta) == rl.stats.near_limit.value(), ctx
            assert int(res.over_delta) == rl.stats.over_limit.value(), ctx


class TestSlabDifferentialVsDict:
    """Randomized stream of batches vs a plain-Python fixed-window model."""

    def test_random_stream(self):
        rng = random.Random(7)
        state = make_slab(1 << 10)
        model: dict[int, tuple[int, int]] = {}  # fp -> (count, window)
        keys = [rng.getrandbits(64) for _ in range(40)]
        now = 10_000

        for step in range(60):
            now += rng.randrange(0, 3)
            items = []
            for _ in range(rng.randrange(1, 12)):
                fp = rng.choice(keys)
                # the real fingerprint embeds the divider (ops/hashing.py), so
                # a given fp always carries one divider — mirror that here
                divider = 1 if fp % 2 == 0 else 60
                items.append((fp, rng.randrange(1, 4), 100, divider))
            state, res = run(state, items, now=now, pad_to=16)

            for i, (fp, hits, limit, divider) in enumerate(items):
                window = (now // divider) * divider
                count, stored_window = model.get(fp, (0, -1))
                if stored_window != window:
                    count = 0
                expect_before = count
                count += hits
                model[fp] = (count, window)
                assert int(res.before[i]) == expect_before, (
                    f"step {step} item {i} fp={fp:x} div={divider} now={now}"
                )
                assert int(res.after[i]) == count


class TestCompactReadbackModes:
    """slab_step_after / slab_step_decided — the production readback modes
    (ops/slab.py compact-modes block)."""

    def _packed(self, items, now, near_ratio=0.8):
        # scalar row needs >= 2 columns; pad with inert all-zero items
        b = max(len(items), 2)
        packed = np.zeros((7, b), dtype=np.uint32)
        for i, (fp, hits, limit, divider) in enumerate(items):
            packed[0, i] = fp & 0xFFFFFFFF
            packed[1, i] = fp >> 32
            packed[2, i] = hits
            packed[3, i] = limit
            packed[4, i] = divider
        packed[6, 0] = np.uint32(now)
        packed[6, 1] = np.float32(near_ratio).view(np.uint32)
        return jnp.asarray(packed)

    def test_decided_mode_codes(self):
        from api_ratelimit_tpu.ops.slab import slab_step_decided

        state = make_slab(N_SLOTS)
        # limit 2/second: hits 1,1,1 in one batch -> OK, OK, OVER
        items = [(KEY_A, 1, 2, 1)] * 3 + [(KEY_B, 1, 100, 1)]
        state, codes, _health = slab_step_decided(state, self._packed(items, now=5_000))
        assert codes.dtype == jnp.uint8
        assert codes.tolist()[:4] == [1, 1, 2, 1]
        # next batch: still over for A within the window
        state, codes, _health = slab_step_decided(state, self._packed(items[:1], now=5_000))
        assert codes.tolist()[:1] == [2]

    def test_after_mode_saturating_cast(self):
        from api_ratelimit_tpu.ops.slab import slab_step_after

        state = make_slab(N_SLOTS)
        items = [(KEY_A, 300, 100, 1)]
        state, after, _health = slab_step_after(
            state, self._packed(items, now=5_000), out_dtype=jnp.uint8
        )
        # 300 saturates the u8 cast; exactness holds because the caller only
        # picks u8 when cap > limit + hits
        assert after.dtype == jnp.uint8
        assert after.tolist()[:1] == [255]
        state, after, _health = slab_step_after(
            state, self._packed([(KEY_B, 3, 100, 1)], now=5_000), out_dtype=jnp.uint16
        )
        assert after.dtype == jnp.uint16
        assert after.tolist()[:1] == [3]

    def test_padding_with_real_fp_reports_zero(self):
        """hits == 0 marks padding and its before/after MUST be 0 even when
        the lane carries a real fingerprint whose probe row matches a live
        stored key (regression: the probe-row reuse briefly leaked the
        stored count into such lanes, which the replicated mesh mode — its
        non-owned lanes are exactly 'real fp, hits 0' — then psum'd into
        other shards' results)."""
        from api_ratelimit_tpu.ops.slab import slab_step_after

        state = make_slab(N_SLOTS)
        state, after, _health = slab_step_after(
            state, self._packed([(KEY_A, 5, 100, 60)], now=5_000)
        )
        assert after.tolist()[0] == 5
        # same key rides a padding lane (hits=0): must come back 0, and the
        # stored counter must not advance
        state, after, _health = slab_step_after(
            state,
            self._packed([(KEY_B, 1, 100, 60), (KEY_A, 0, 100, 60)], now=5_000),
        )
        assert after.tolist()[:2] == [1, 0]
        state, after, _health = slab_step_after(
            state, self._packed([(KEY_A, 1, 100, 60)], now=5_000)
        )
        assert after.tolist()[0] == 6  # 5 + 1, untouched by the padding lane


class TestSlabHealth:
    """The slab's lossy behaviors must be counted, not silent (ops/slab.py
    docstring): the eviction mix (expired / window-ended / live) and
    within-batch contention drops, plus the live-slot occupancy gauge.
    Health layout: uint32[4] = (evict_expired, evict_window, evict_live,
    drops) — ops/slab.py HEALTH_* indices."""

    def test_no_loss_on_clean_traffic(self):
        state = make_slab(N_SLOTS)
        state, res = run(state, [(KEY_A, 1, 10, 60), (KEY_B, 1, 10, 60)], now=1000)
        assert [int(v) for v in res.health] == [0, 0, 0, 0, 0]

    def test_within_batch_contention_drop_counted(self):
        # 4 sets x 1 way: three distinct keys with equal fp_lo mod 4 fight
        # for one way; one write wins, two drop (and fail open — their
        # counts restart)
        state = make_slab(4)
        keys = [(0x0 << 32) | 0x10, (0x1 << 32) | 0x20, (0x2 << 32) | 0x30]
        state, res = run(state, [(k, 1, 10, 60) for k in keys], now=1000, ways=1)
        ev_exp, ev_win, ev_live, drops, _resets = (int(v) for v in res.health)
        assert drops == 2
        assert (ev_exp, ev_win, ev_live) == (0, 0, 0)  # fresh ways: no evict
        # every item still got a decision (fail open)
        assert [int(a) for a in res.after] == [1, 1, 1]

    def test_live_eviction_counted_lowest_count_first(self):
        # one 2-way set, both ways live in open windows with different
        # counts: a third key must evict the LOWEST-COUNT live way
        state = make_slab(2)
        heavy = (0x5 << 32) | 0x0
        light = (0x6 << 32) | 0x1
        state, _ = run(state, [(heavy, 5, 100, 60)], now=1000, ways=2)
        state, res = run(state, [(light, 1, 100, 60)], now=1000, ways=2)
        assert [int(v) for v in res.health] == [0, 0, 0, 0, 0]
        state, res = run(state, [((0x7 << 32) | 0x2, 1, 100, 60)], now=1000, ways=2)
        ev_exp, ev_win, ev_live, drops, _resets = (int(v) for v in res.health)
        assert (ev_exp, ev_win, ev_live, drops) == (0, 0, 1, 0)
        assert int(res.after[0]) == 1  # the evictor starts fresh
        # the heavy key survived (the light one was the victim)
        state, res = run(state, [(heavy, 1, 100, 60)], now=1000, ways=2)
        assert int(res.before[0]) == 5

    def test_window_ended_evicts_before_live(self):
        # one 2-way set: way A live in an OPEN window, way B live by TTL
        # but its fixed window ended — the insert must take B
        state = make_slab(2)
        open_key = (0x5 << 32) | 0x0
        ended_key = (0x6 << 32) | 0x1
        # ended_key: 1s window + large jitter pins the slot past rollover
        state, _ = run(state, [(ended_key, 7, 100, 1, 300)], now=1000, ways=2)
        state, _ = run(state, [(open_key, 3, 100, 3600)], now=1002, ways=2)
        state, res = run(state, [((0x7 << 32) | 0x2, 1, 100, 60)], now=1002, ways=2)
        ev_exp, ev_win, ev_live, drops, _resets = (int(v) for v in res.health)
        assert (ev_exp, ev_win, ev_live, drops) == (0, 1, 0, 0)
        # the open-window counter survived
        state, res = run(state, [(open_key, 1, 100, 3600)], now=1002, ways=2)
        assert int(res.before[0]) == 3

    def test_expired_reclaim_counted_before_any_live(self):
        # one 2-way set: one expired (dead) way, one live — the insert
        # reuses the dead way and counts an expired reclaim, never a
        # live eviction
        state = make_slab(2)
        dead_key = (0x5 << 32) | 0x0
        live_key = (0x6 << 32) | 0x1
        state, _ = run(state, [(dead_key, 2, 100, 1)], now=1000, ways=2)
        state, _ = run(state, [(live_key, 4, 100, 3600)], now=2000, ways=2)
        state, res = run(state, [((0x7 << 32) | 0x2, 1, 100, 60)], now=2000, ways=2)
        ev_exp, ev_win, ev_live, drops, _resets = (int(v) for v in res.health)
        assert (ev_exp, ev_win, ev_live, drops) == (1, 0, 0, 0)
        state, res = run(state, [(live_key, 1, 100, 3600)], now=2000, ways=2)
        assert int(res.before[0]) == 4

    def test_same_batch_winner_never_evicted(self):
        # a key that MATCHES a live row in this batch must survive an
        # evictor colliding with its way in the same batch: the evictor's
        # write drops (counted), the matcher's increment persists
        state = make_slab(1)  # one set, one way: maximum contention
        a = (0x5 << 32) | 0x0
        b = (0x6 << 32) | 0x1
        state, _ = run(state, [(a, 2, 100, 3600)], now=1000, ways=1)
        # same batch: a matches its live row, b would have to evict it
        state, res = run(state, [(b, 1, 100, 3600), (a, 1, 100, 3600)], now=1000, ways=1)
        assert [int(x) for x in res.after] == [1, 3]
        ev_exp, ev_win, ev_live, drops, _resets = (int(v) for v in res.health)
        assert drops == 1  # b's insert lost
        assert ev_live == 0  # and displaced nothing
        state, res = run(state, [(a, 1, 100, 3600)], now=1000, ways=1)
        assert int(res.before[0]) == 3  # a's chain unbroken

    def test_live_slots_occupancy(self):
        from api_ratelimit_tpu.ops.slab import slab_live_slots

        state = make_slab(N_SLOTS)
        assert int(slab_live_slots(state, 1000)) == 0
        state, _ = run(state, [(KEY_A, 1, 10, 60), (KEY_B, 1, 10, 60)], now=1000)
        assert int(slab_live_slots(state, 1000)) == 2
        # both windows expire (divider 60, no jitter): occupancy decays
        assert int(slab_live_slots(state, 1061)) == 0


class TestFloorDivExact:
    """floor_div_exact_* replaced every vector integer division on the device
    path (XLA/Mosaic expand vector idiv into a ~32-pass loop, ~100ms per site
    at batch 2^20 on v5e — the round-3 perf gap). The float32-assisted
    formula must match numpy's // EXACTLY over the full operand ranges the
    contracts allow, or window starts / throttle pacing silently drift."""

    def test_i32_exhaustive_edges(self):
        from api_ratelimit_tpu.ops.decide import floor_div_exact_i32

        nows = [0, 1, 59, 60, 61, 3599, 3600, 86399, 86400, 86401,
                1_700_000_000, 2**31 - 1]
        divs = [1, 2, 59, 60, 3600, 86400, 86401, 2**24 - 1, 2**24,
                2**30, 2**31 - 1]
        a = np.array([n for n in nows for _ in divs], dtype=np.int32)
        b = np.array([d for _ in nows for d in divs], dtype=np.int32)
        got = np.asarray(floor_div_exact_i32(jnp.asarray(a), jnp.asarray(b)))
        want = a.astype(np.int64) // b.astype(np.int64)
        np.testing.assert_array_equal(got, want.astype(np.int32))

    def test_i32_randomized(self):
        from api_ratelimit_tpu.ops.decide import floor_div_exact_i32

        rng = np.random.RandomState(7)
        a = rng.randint(0, 2**31, size=1 << 16).astype(np.int32)
        b = rng.randint(1, 2**31, size=1 << 16).astype(np.int32)
        # half the divisors small (the realistic unit-divider regime)
        b[::2] = rng.choice([1, 60, 3600, 86400], size=(1 << 15)).astype(np.int32)
        got = np.asarray(floor_div_exact_i32(jnp.asarray(a), jnp.asarray(b)))
        want = (a.astype(np.int64) // b.astype(np.int64)).astype(np.int32)
        np.testing.assert_array_equal(got, want)

    def test_u32_big_divisor_short_circuits(self):
        from api_ratelimit_tpu.ops.decide import floor_div_exact_u32

        a = np.array([0, 1, 2**27 - 1, 2**31 - 1], dtype=np.uint32)
        b = np.array([2**31, 2**32 - 1, 2**31 + 5, 2**31], dtype=np.uint32)
        got = np.asarray(floor_div_exact_u32(jnp.asarray(a), jnp.asarray(b)))
        np.testing.assert_array_equal(got, np.zeros(4, np.uint32))

    def test_i32_reciprocal_edges(self):
        # the Newton reciprocal must stay inside the fixup band across
        # exponent boundaries: powers of two, power-of-two +-1, and maximal
        # quotients against each
        from api_ratelimit_tpu.ops.decide import floor_div_exact_i32

        bs = []
        for k in range(0, 31):
            bs += [1 << k, (1 << k) + 1, max(1, (1 << k) - 1)]
        b = np.array(sorted(set(bs)), dtype=np.int32)
        for a_val in (0, 1, 2**30, 2**31 - 1):
            a = np.full_like(b, a_val)
            got = np.asarray(
                floor_div_exact_i32(jnp.asarray(a), jnp.asarray(b))
            )
            want = (a.astype(np.int64) // b.astype(np.int64)).astype(np.int32)
            np.testing.assert_array_equal(got, want, err_msg=f"a={a_val}")

    def test_u32_randomized(self):
        from api_ratelimit_tpu.ops.decide import floor_div_exact_u32

        rng = np.random.RandomState(11)
        a = rng.randint(0, 2**31, size=1 << 16).astype(np.uint32)
        b = (rng.randint(1, 2**32, size=1 << 16)).astype(np.uint32)
        got = np.asarray(floor_div_exact_u32(jnp.asarray(a), jnp.asarray(b)))
        want = (a.astype(np.uint64) // b.astype(np.uint64)).astype(np.uint32)
        np.testing.assert_array_equal(got, want)


class TestPackbitsMuladd:
    """The multiply-add packbits twin (the candidate packbits swap if
    on-chip attribution shows the shift/or lowering is pathological) must
    bit-match numpy's big-endian packbits on every mask shape the engine
    ships. Hardware parity is pinned in tests/test_pallas_tpu.py, the
    floor_div precedent."""

    def test_matches_numpy(self):
        from api_ratelimit_tpu.ops.decide import packbits_muladd

        rng = np.random.RandomState(13)
        for size in (128, 1 << 12, 1 << 16):
            mask = rng.rand(size) < 0.37
            got = np.asarray(packbits_muladd(jnp.asarray(mask)))
            np.testing.assert_array_equal(got, np.packbits(mask))
        # all-zeros / all-ones edges
        for mask in (np.zeros(256, bool), np.ones(256, bool)):
            np.testing.assert_array_equal(
                np.asarray(packbits_muladd(jnp.asarray(mask))), np.packbits(mask)
            )
