"""Differential fuzz: the set-associative device step vs the exact host
oracle (testing/oracle.py SetSlabOracle) AT AND PAST 100% occupancy.

The open-addressed slab could only be fuzzed below saturation (past it,
admission shed and the stream stopped being comparable). The set-associative
layout makes overload a TESTABLE regime: eviction is deterministic (dead,
then window-ended, then lowest-count live, rotation tiebreak — never a
same-batch winner), so the oracle models the step bit-for-bit — per-item
before/after/code, the final table, and the eviction mix — while offered
live-key load sits well past capacity.

Campaign style follows tests/test_race.py's SLAB_FUZZ_EXAMPLES contract,
but seeded-numpy rather than hypothesis (the image ships without it, and a
skipped fuzz campaign protects nothing): small default example counts keep
`make tests_unit` fast; an extended idle-hardware campaign sets
SLAB_FUZZ_EXAMPLES (e.g. 2000) to mine the same properties much deeper.
Every failure message carries the (seed, step) pair that reproduces it.
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np

from api_ratelimit_tpu.ops.slab import (
    ALGO_CONC_RELEASE,
    ALGO_CONCURRENCY,
    ALGO_FIXED_WINDOW,
    ALGO_GCRA,
    ALGO_SHIFT,
    ALGO_SLIDING_WINDOW,
    OUT_AFTER,
    OUT_BEFORE,
    OUT_CODE,
    OUT_ORDER,
    ROW_DIVIDER,
    ROW_FP_HI,
    ROW_FP_LO,
    ROW_HITS,
    ROW_JITTER,
    ROW_LIMIT,
    ROW_SCALARS,
    make_slab,
    slab_step_packed,
    validate_ways,
)
from api_ratelimit_tpu.testing.oracle import SetSlabOracle

FUZZ_EXAMPLES = int(os.environ.get("SLAB_FUZZ_EXAMPLES", "0") or 0)


def _fmix32(x: int) -> int:
    x &= 0xFFFFFFFF
    x ^= x >> 16
    x = (x * 0x85EBCA6B) & 0xFFFFFFFF
    x ^= x >> 13
    x = (x * 0xC2B2AE35) & 0xFFFFFFFF
    return x ^ (x >> 16)


def _fp(key_id: int) -> tuple[int, int]:
    """(fp_lo, fp_hi) for a fuzz key: fp_lo well mixed (set spread);
    fp_hi's TOP 16 bits carry the unique key id (so the oracle's
    winner-per-way rule is exact — see SetSlabOracle docstring) and its
    low 16 bits are mixed (they feed the way-preference rotation)."""
    return (
        _fmix32(key_id),
        (((key_id + 1) & 0xFFFF) << 16) | (_fmix32(key_id ^ 0xA5A5) & 0xFFFF),
    )


def _pack(items, now: int, pad_to: int) -> np.ndarray:
    """items: (fp_lo, fp_hi, hits, limit, divider, jitter) -> uint32[7, b]."""
    packed = np.zeros((7, pad_to), dtype=np.uint32)
    for i, (fp_lo, fp_hi, hits, limit, div, jit) in enumerate(items):
        packed[ROW_FP_LO, i] = fp_lo
        packed[ROW_FP_HI, i] = fp_hi
        packed[ROW_HITS, i] = hits
        packed[ROW_LIMIT, i] = limit
        packed[ROW_DIVIDER, i] = div
        packed[ROW_JITTER, i] = jit
    packed[ROW_SCALARS, 0] = np.uint32(now)
    packed[ROW_SCALARS, 1] = np.float32(0.8).view(np.uint32)
    return packed


class _Harness:
    """Drives the device step and the host oracle in lockstep and compares
    every observable: per-item before/after/code, the per-batch health
    vector, and (on demand) the whole row table."""

    def __init__(self, n_slots: int, ways: int, pad_to: int):
        self.state = make_slab(n_slots)
        # the same clamp the engine applies (tiny slab => fully associative)
        self.ways = validate_ways(n_slots, ways)
        self.oracle = SetSlabOracle(n_slots, ways)
        self.pad_to = pad_to

    def step(self, items, now: int, label=""):
        assert len(items) <= self.pad_to
        packed = _pack(items, now, self.pad_to)
        self.state, out, health = slab_step_packed(
            self.state, jnp.asarray(packed), ways=self.ways
        )
        out = np.asarray(out)
        order = out[OUT_ORDER].astype(np.int64)
        got = {}
        for name, row in (
            ("before", OUT_BEFORE),
            ("after", OUT_AFTER),
            ("code", OUT_CODE),
        ):
            arr = np.empty(self.pad_to, dtype=np.uint32)
            arr[order] = out[row]
            got[name] = arr
        w_before, w_after, w_codes, w_delta = self.oracle.step_batch(items, now)
        for i, (_fp_lo, _fp_hi, hits, _l, _d, _j) in enumerate(items):
            if hits <= 0:
                continue
            ctx = (label, i, items[i])
            assert int(got["before"][i]) == w_before[i], ctx
            assert int(got["after"][i]) == w_after[i], ctx
            assert int(got["code"][i]) == w_codes[i], ctx
        assert [int(v) for v in np.asarray(health)] == w_delta, label
        return got

    def assert_tables_equal(self, label=""):
        dev = np.asarray(self.state.table).astype(np.uint64)
        np.testing.assert_array_equal(dev, self.oracle.table, err_msg=str(label))


class TestFuzzSequentialOverCapacity:
    """Random op streams over a key pool 3x slab capacity: every decision,
    every eviction choice, and the final table must match the oracle
    exactly — the >100%-occupancy regime the old layout could not serve."""

    def test_stream_matches_oracle(self):
        examples = FUZZ_EXAMPLES or 25
        for seed in range(examples):
            rng = np.random.default_rng(seed)
            h = _Harness(n_slots=16, ways=4, pad_to=8)
            limit = int(rng.integers(1, 7))
            now = 700_000
            for step in range(int(rng.integers(1, 51))):
                key_id = int(rng.integers(0, 48))  # 48 keys, 16 slots
                hits = int(rng.integers(1, 4))
                now += int(rng.integers(0, 91))
                fp_lo, fp_hi = _fp(key_id)
                # divider/jitter derived from the key (production
                # fingerprints include the window unit, so one fp == one
                # divider)
                div = 60 if key_id % 2 else 5
                jit = key_id % 7
                h.step(
                    [(fp_lo, fp_hi, hits, limit, div, jit)],
                    now,
                    label=(seed, step, key_id),
                )
            h.assert_tables_equal(label=seed)

    def test_fully_associative_clamp_matches_oracle(self):
        """Tiny slabs clamp ways to n_slots (one fully associative set);
        the oracle must agree there too."""
        examples = FUZZ_EXAMPLES or 10
        for seed in range(examples):
            rng = np.random.default_rng(10_000 + seed)
            h = _Harness(n_slots=8, ways=128, pad_to=8)  # clamps to ways=8
            now = 700_000
            for step in range(20):
                now += int(rng.integers(0, 30))
                key_id = int(rng.integers(0, 24))
                fp_lo, fp_hi = _fp(key_id)
                h.step([(fp_lo, fp_hi, 1, 4, 30, 0)], now, label=(seed, step))
            h.assert_tables_equal(label=seed)


class TestFuzzDuplicateHeavyBatches:
    """Batched streams with heavy in-batch duplication and way contention:
    duplicate serialization, the winner-per-way rule, and the counted
    drops must all match the oracle item-for-item."""

    def test_batches_match_oracle(self):
        examples = FUZZ_EXAMPLES or 25
        for seed in range(examples):
            rng = np.random.default_rng(20_000 + seed)
            h = _Harness(n_slots=16, ways=4, pad_to=16)
            limit = int(rng.integers(1, 10))
            now = 700_000
            for batch_no in range(int(rng.integers(1, 9))):
                now += int(rng.integers(0, 31))
                size = int(rng.integers(1, 17))
                # 24 keys over 16 slots: duplicates AND way contention
                batch = [
                    (int(rng.integers(0, 24)), int(rng.integers(1, 5)))
                    for _ in range(size)
                ]
                items = [
                    (*_fp(key_id), hits, limit, 60, key_id % 5)
                    for key_id, hits in batch
                ]
                h.step(items, now, label=(seed, batch_no, batch))
            h.assert_tables_equal(label=seed)


class TestMidWindowEvictThenReinsert:
    """The lossy tier, pinned end to end: a full set evicts its
    lowest-count live way; the evicted key re-inserts MID-WINDOW and
    restarts from zero (the fail-open posture on a lost counter) — and
    the oracle agrees at every step."""

    def test_evict_reinsert_cycle(self):
        h = _Harness(n_slots=4, ways=4, pad_to=8)
        now = 700_000
        keys = [_fp(i) for i in range(5)]
        counts = [5, 4, 3, 2]
        for (fp_lo, fp_hi), c in zip(keys[:4], counts):
            h.step([(fp_lo, fp_hi, c, 100, 3600, 0)], now)
        occupied = h.oracle.table[:, 4] > now
        assert occupied.all()  # one full 4-way set
        # key E: the set is full of live in-window rows — the LOWEST-COUNT
        # way (key D, count 2) is the victim
        now += 10
        h.step([(*keys[4], 1, 100, 3600, 0)], now, label="insert E")
        assert h.oracle.health[2] == 1  # one live eviction
        d_lo, d_hi = keys[3]
        stored_fps = set(h.oracle.table[:, 0].tolist())
        assert d_lo not in stored_fps
        # key D returns mid-window: its counter RESTARTED (before == 0,
        # fail open), displacing the current lowest-count way (E, count 1)
        now += 10
        got = h.step([(d_lo, d_hi, 1, 100, 3600, 0)], now, label="reinsert D")
        assert int(got["before"][0]) == 0 and int(got["after"][0]) == 1
        assert h.oracle.health[2] == 2
        # the high-count survivors kept exact counts through both evictions
        for (fp_lo, fp_hi), c in zip(keys[:3], counts[:3]):
            got = h.step([(fp_lo, fp_hi, 1, 100, 3600, 0)], now, label="survivor")
            assert int(got["before"][0]) == c
        h.assert_tables_equal()


class TestFuzzMixedAlgorithmBatches:
    """Differential fuzz of the sibling decision kernels: fixed-window,
    sliding-window, GCRA, and concurrency keys INTERLEAVED in one launch,
    bit-exact against the multi-algorithm host oracle — per-item
    before/after/code, the health vector (including algorithm-change
    resets), and the final row table. Each algorithm clears >= 10k fuzzed
    decisions across the campaign classes below (the acceptance bar);
    SLAB_FUZZ_EXAMPLES deepens it on idle hardware."""

    # one stable rule per key id: the production invariant (one fp == one
    # rule == one algorithm per config generation)
    @staticmethod
    def _rule(key_id: int):
        algo = (
            ALGO_FIXED_WINDOW,
            ALGO_SLIDING_WINDOW,
            ALGO_GCRA,
            ALGO_CONCURRENCY,
        )[key_id % 4]
        limit = 2 + key_id % 7
        div = (5, 30, 60)[key_id % 3]
        jit = key_id % 5
        return algo, limit, div, jit

    def _item(self, key_id: int, hits: int, release: bool = False):
        algo, limit, div, jit = self._rule(key_id)
        if release and algo == ALGO_CONCURRENCY:
            algo = ALGO_CONC_RELEASE
        return (*_fp(key_id), hits, limit, div | (algo << ALGO_SHIFT), jit)

    def test_interleaved_streams_match_oracle(self):
        examples = FUZZ_EXAMPLES or 40
        per_algo = [0, 0, 0, 0]
        for seed in range(examples):
            rng = np.random.default_rng(30_000 + seed)
            h = _Harness(n_slots=32, ways=4, pad_to=32)
            now = 700_000
            for batch_no in range(10):
                now += int(rng.integers(0, 40))
                size = int(rng.integers(1, 33))
                items = []
                for _ in range(size):
                    key_id = int(rng.integers(0, 40))
                    release = bool(rng.integers(0, 3) == 0)
                    items.append(
                        self._item(key_id, int(rng.integers(1, 4)), release)
                    )
                    per_algo[key_id % 4] += 1
                h.step(items, now, label=(seed, batch_no))
            h.assert_tables_equal(label=seed)
        # every algorithm genuinely interleaves in this class (the >= 10k
        # per-algorithm depth bar is test_per_algorithm_depth's job)
        assert all(n >= 1000 for n in per_algo), per_algo
        assert sum(per_algo) >= 5_000

    def test_per_algorithm_depth(self):
        """>= 10k decisions per NON-FIXED algorithm (fixed clears its own
        bar in the legacy classes above), duplicate-heavy so the segment
        serialization rules (GCRA admit prefix, concurrency
        acquire/release ordering, sliding carry) are hammered."""
        for algo_base in (1, 2, 3):  # sliding, gcra, concurrency key ids
            done = 0
            seed0 = 50_000 + algo_base
            batch_no = 0
            h = _Harness(n_slots=16, ways=4, pad_to=64)
            rng = np.random.default_rng(seed0)
            now = 800_000
            while done < 10_000:
                now += int(rng.integers(0, 25))
                size = int(rng.integers(32, 65))
                items = []
                for _ in range(size):
                    # 12 keys of this algorithm: heavy duplication + way
                    # contention in every batch
                    key_id = algo_base + 4 * int(rng.integers(0, 12))
                    release = bool(rng.integers(0, 3) == 0)
                    items.append(
                        self._item(key_id, int(rng.integers(1, 4)), release)
                    )
                h.step(items, now, label=(seed0, batch_no))
                done += size
                batch_no += 1
            h.assert_tables_equal(label=seed0)
            assert done >= 10_000

    def test_algorithm_change_on_reload_resets_and_counts(self):
        """Mid-window algorithm change (a hot reload swapping a rule's
        algorithm between launches): the fingerprint still matches the
        row, but the stored state resets to zero and the reset is counted
        in the health vector — on both the kernel and the oracle."""
        h = _Harness(n_slots=8, ways=4, pad_to=8)
        now = 700_000
        fp_lo, fp_hi = _fp(7)
        fixed = (fp_lo, fp_hi, 1, 10, 60, 0)
        for _ in range(5):
            h.step([fixed], now)
        assert int(h.oracle.table[:, 2].max()) == 5
        # reload flips the rule to GCRA mid-window: same fp, state resets
        gcra = (fp_lo, fp_hi, 1, 10, 60 | (ALGO_GCRA << ALGO_SHIFT), 0)
        got = h.step([gcra], now, label="algo flip")
        assert int(got["after"][0]) == 1  # fresh TAT, not counter 6
        assert h.oracle.health[4] == 1  # the reset is counted
        # and flipping back resets again, counted again
        got = h.step([fixed], now, label="flip back")
        assert int(got["before"][0]) == 0 and int(got["after"][0]) == 1
        assert h.oracle.health[4] == 2
        h.assert_tables_equal(label="algo change")


class TestAtScaleOneSidedParity:
    """parity_report's contract at 120% offered live-key load: the slab
    may fail OPEN (false_ok — a counted eviction/drop), never CLOSED
    (false_over must be 0 at any occupancy)."""

    def test_false_over_is_zero_past_capacity(self):
        from api_ratelimit_tpu.testing.oracle import parity_report

        n_slots, ways, batch = 1024, 128, 64
        n_keys = int(n_slots * 1.2)  # 120% of capacity, one shared window
        rng = np.random.default_rng(11)
        ids = rng.integers(0, n_keys, size=4096).astype(np.int64)
        codes = np.empty(ids.size, dtype=np.uint32)
        now, limit = 700_000, 3
        state = make_slab(n_slots)
        for off in range(0, ids.size, batch):
            chunk = ids[off : off + batch]
            items = [(*_fp(int(k)), 1, limit, 3600, 0) for k in chunk]
            packed = _pack(items, now, batch)
            state, out, _health = slab_step_packed(
                state, jnp.asarray(packed), ways=ways
            )
            out = np.asarray(out)
            order = out[OUT_ORDER].astype(np.int64)
            arr = np.empty(batch, dtype=np.uint32)
            arr[order] = out[OUT_CODE]
            codes[off : off + chunk.size] = arr[: chunk.size]
        report = parity_report(ids, codes, limit)
        assert report["false_over"] == 0
        assert report["oracle_over_frac"] > 0.05  # the stream really saturates
        # past-capacity eviction costs SOME open failures, but bounded ones
        assert report["agreement"] > 0.5
