"""Differential fuzz: the set-associative device step vs the exact host
oracle (testing/oracle.py SetSlabOracle) AT AND PAST 100% occupancy.

The open-addressed slab could only be fuzzed below saturation (past it,
admission shed and the stream stopped being comparable). The set-associative
layout makes overload a TESTABLE regime: eviction is deterministic (dead,
then window-ended, then lowest-count live, rotation tiebreak — never a
same-batch winner), so the oracle models the step bit-for-bit — per-item
before/after/code, the final table, and the eviction mix — while offered
live-key load sits well past capacity.

Campaign style follows tests/test_race.py's SLAB_FUZZ_EXAMPLES contract,
but seeded-numpy rather than hypothesis (the image ships without it, and a
skipped fuzz campaign protects nothing): small default example counts keep
`make tests_unit` fast; an extended idle-hardware campaign sets
SLAB_FUZZ_EXAMPLES (e.g. 2000) to mine the same properties much deeper.
Every failure message carries the (seed, step) pair that reproduces it.
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np

from api_ratelimit_tpu.ops.slab import (
    OUT_AFTER,
    OUT_BEFORE,
    OUT_CODE,
    OUT_ORDER,
    ROW_DIVIDER,
    ROW_FP_HI,
    ROW_FP_LO,
    ROW_HITS,
    ROW_JITTER,
    ROW_LIMIT,
    ROW_SCALARS,
    make_slab,
    slab_step_packed,
    validate_ways,
)
from api_ratelimit_tpu.testing.oracle import SetSlabOracle

FUZZ_EXAMPLES = int(os.environ.get("SLAB_FUZZ_EXAMPLES", "0") or 0)


def _fmix32(x: int) -> int:
    x &= 0xFFFFFFFF
    x ^= x >> 16
    x = (x * 0x85EBCA6B) & 0xFFFFFFFF
    x ^= x >> 13
    x = (x * 0xC2B2AE35) & 0xFFFFFFFF
    return x ^ (x >> 16)


def _fp(key_id: int) -> tuple[int, int]:
    """(fp_lo, fp_hi) for a fuzz key: fp_lo well mixed (set spread);
    fp_hi's TOP 16 bits carry the unique key id (so the oracle's
    winner-per-way rule is exact — see SetSlabOracle docstring) and its
    low 16 bits are mixed (they feed the way-preference rotation)."""
    return (
        _fmix32(key_id),
        (((key_id + 1) & 0xFFFF) << 16) | (_fmix32(key_id ^ 0xA5A5) & 0xFFFF),
    )


def _pack(items, now: int, pad_to: int) -> np.ndarray:
    """items: (fp_lo, fp_hi, hits, limit, divider, jitter) -> uint32[7, b]."""
    packed = np.zeros((7, pad_to), dtype=np.uint32)
    for i, (fp_lo, fp_hi, hits, limit, div, jit) in enumerate(items):
        packed[ROW_FP_LO, i] = fp_lo
        packed[ROW_FP_HI, i] = fp_hi
        packed[ROW_HITS, i] = hits
        packed[ROW_LIMIT, i] = limit
        packed[ROW_DIVIDER, i] = div
        packed[ROW_JITTER, i] = jit
    packed[ROW_SCALARS, 0] = np.uint32(now)
    packed[ROW_SCALARS, 1] = np.float32(0.8).view(np.uint32)
    return packed


class _Harness:
    """Drives the device step and the host oracle in lockstep and compares
    every observable: per-item before/after/code, the per-batch health
    vector, and (on demand) the whole row table."""

    def __init__(self, n_slots: int, ways: int, pad_to: int):
        self.state = make_slab(n_slots)
        # the same clamp the engine applies (tiny slab => fully associative)
        self.ways = validate_ways(n_slots, ways)
        self.oracle = SetSlabOracle(n_slots, ways)
        self.pad_to = pad_to

    def step(self, items, now: int, label=""):
        assert len(items) <= self.pad_to
        packed = _pack(items, now, self.pad_to)
        self.state, out, health = slab_step_packed(
            self.state, jnp.asarray(packed), ways=self.ways
        )
        out = np.asarray(out)
        order = out[OUT_ORDER].astype(np.int64)
        got = {}
        for name, row in (
            ("before", OUT_BEFORE),
            ("after", OUT_AFTER),
            ("code", OUT_CODE),
        ):
            arr = np.empty(self.pad_to, dtype=np.uint32)
            arr[order] = out[row]
            got[name] = arr
        w_before, w_after, w_codes, w_delta = self.oracle.step_batch(items, now)
        for i, (_fp_lo, _fp_hi, hits, _l, _d, _j) in enumerate(items):
            if hits <= 0:
                continue
            ctx = (label, i, items[i])
            assert int(got["before"][i]) == w_before[i], ctx
            assert int(got["after"][i]) == w_after[i], ctx
            assert int(got["code"][i]) == w_codes[i], ctx
        assert [int(v) for v in np.asarray(health)] == w_delta, label
        return got

    def assert_tables_equal(self, label=""):
        dev = np.asarray(self.state.table).astype(np.uint64)
        np.testing.assert_array_equal(dev, self.oracle.table, err_msg=str(label))


class TestFuzzSequentialOverCapacity:
    """Random op streams over a key pool 3x slab capacity: every decision,
    every eviction choice, and the final table must match the oracle
    exactly — the >100%-occupancy regime the old layout could not serve."""

    def test_stream_matches_oracle(self):
        examples = FUZZ_EXAMPLES or 25
        for seed in range(examples):
            rng = np.random.default_rng(seed)
            h = _Harness(n_slots=16, ways=4, pad_to=8)
            limit = int(rng.integers(1, 7))
            now = 700_000
            for step in range(int(rng.integers(1, 51))):
                key_id = int(rng.integers(0, 48))  # 48 keys, 16 slots
                hits = int(rng.integers(1, 4))
                now += int(rng.integers(0, 91))
                fp_lo, fp_hi = _fp(key_id)
                # divider/jitter derived from the key (production
                # fingerprints include the window unit, so one fp == one
                # divider)
                div = 60 if key_id % 2 else 5
                jit = key_id % 7
                h.step(
                    [(fp_lo, fp_hi, hits, limit, div, jit)],
                    now,
                    label=(seed, step, key_id),
                )
            h.assert_tables_equal(label=seed)

    def test_fully_associative_clamp_matches_oracle(self):
        """Tiny slabs clamp ways to n_slots (one fully associative set);
        the oracle must agree there too."""
        examples = FUZZ_EXAMPLES or 10
        for seed in range(examples):
            rng = np.random.default_rng(10_000 + seed)
            h = _Harness(n_slots=8, ways=128, pad_to=8)  # clamps to ways=8
            now = 700_000
            for step in range(20):
                now += int(rng.integers(0, 30))
                key_id = int(rng.integers(0, 24))
                fp_lo, fp_hi = _fp(key_id)
                h.step([(fp_lo, fp_hi, 1, 4, 30, 0)], now, label=(seed, step))
            h.assert_tables_equal(label=seed)


class TestFuzzDuplicateHeavyBatches:
    """Batched streams with heavy in-batch duplication and way contention:
    duplicate serialization, the winner-per-way rule, and the counted
    drops must all match the oracle item-for-item."""

    def test_batches_match_oracle(self):
        examples = FUZZ_EXAMPLES or 25
        for seed in range(examples):
            rng = np.random.default_rng(20_000 + seed)
            h = _Harness(n_slots=16, ways=4, pad_to=16)
            limit = int(rng.integers(1, 10))
            now = 700_000
            for batch_no in range(int(rng.integers(1, 9))):
                now += int(rng.integers(0, 31))
                size = int(rng.integers(1, 17))
                # 24 keys over 16 slots: duplicates AND way contention
                batch = [
                    (int(rng.integers(0, 24)), int(rng.integers(1, 5)))
                    for _ in range(size)
                ]
                items = [
                    (*_fp(key_id), hits, limit, 60, key_id % 5)
                    for key_id, hits in batch
                ]
                h.step(items, now, label=(seed, batch_no, batch))
            h.assert_tables_equal(label=seed)


class TestMidWindowEvictThenReinsert:
    """The lossy tier, pinned end to end: a full set evicts its
    lowest-count live way; the evicted key re-inserts MID-WINDOW and
    restarts from zero (the fail-open posture on a lost counter) — and
    the oracle agrees at every step."""

    def test_evict_reinsert_cycle(self):
        h = _Harness(n_slots=4, ways=4, pad_to=8)
        now = 700_000
        keys = [_fp(i) for i in range(5)]
        counts = [5, 4, 3, 2]
        for (fp_lo, fp_hi), c in zip(keys[:4], counts):
            h.step([(fp_lo, fp_hi, c, 100, 3600, 0)], now)
        occupied = h.oracle.table[:, 4] > now
        assert occupied.all()  # one full 4-way set
        # key E: the set is full of live in-window rows — the LOWEST-COUNT
        # way (key D, count 2) is the victim
        now += 10
        h.step([(*keys[4], 1, 100, 3600, 0)], now, label="insert E")
        assert h.oracle.health[2] == 1  # one live eviction
        d_lo, d_hi = keys[3]
        stored_fps = set(h.oracle.table[:, 0].tolist())
        assert d_lo not in stored_fps
        # key D returns mid-window: its counter RESTARTED (before == 0,
        # fail open), displacing the current lowest-count way (E, count 1)
        now += 10
        got = h.step([(d_lo, d_hi, 1, 100, 3600, 0)], now, label="reinsert D")
        assert int(got["before"][0]) == 0 and int(got["after"][0]) == 1
        assert h.oracle.health[2] == 2
        # the high-count survivors kept exact counts through both evictions
        for (fp_lo, fp_hi), c in zip(keys[:3], counts[:3]):
            got = h.step([(fp_lo, fp_hi, 1, 100, 3600, 0)], now, label="survivor")
            assert int(got["before"][0]) == c
        h.assert_tables_equal()


class TestAtScaleOneSidedParity:
    """parity_report's contract at 120% offered live-key load: the slab
    may fail OPEN (false_ok — a counted eviction/drop), never CLOSED
    (false_over must be 0 at any occupancy)."""

    def test_false_over_is_zero_past_capacity(self):
        from api_ratelimit_tpu.testing.oracle import parity_report

        n_slots, ways, batch = 1024, 128, 64
        n_keys = int(n_slots * 1.2)  # 120% of capacity, one shared window
        rng = np.random.default_rng(11)
        ids = rng.integers(0, n_keys, size=4096).astype(np.int64)
        codes = np.empty(ids.size, dtype=np.uint32)
        now, limit = 700_000, 3
        state = make_slab(n_slots)
        for off in range(0, ids.size, batch):
            chunk = ids[off : off + batch]
            items = [(*_fp(int(k)), 1, limit, 3600, 0) for k in chunk]
            packed = _pack(items, now, batch)
            state, out, _health = slab_step_packed(
                state, jnp.asarray(packed), ways=ways
            )
            out = np.asarray(out)
            order = out[OUT_ORDER].astype(np.int64)
            arr = np.empty(batch, dtype=np.uint32)
            arr[order] = out[OUT_CODE]
            codes[off : off + chunk.size] = arr[: chunk.size]
        report = parity_report(ids, codes, limit)
        assert report["false_over"] == 0
        assert report["oracle_over_frac"] > 0.05  # the stream really saturates
        # past-capacity eviction costs SOME open failures, but bounded ones
        assert report["agreement"] > 0.5
