"""Unit tests for the stats pipeline."""

from api_ratelimit_tpu.stats import Store, TestSink, StatsdSink


def test_counter_flush_delta(test_store):
    store, sink = test_store
    c = store.scope("ratelimit").counter("hits")
    c.add(5)
    c.inc()
    store.flush()
    assert sink.counters == {"ratelimit.hits": 6}
    # second flush with no activity emits nothing new
    store.flush()
    assert sink.counters == {"ratelimit.hits": 6}
    c.inc()
    store.flush()
    assert sink.counters == {"ratelimit.hits": 7}


def test_scope_nesting_and_caching(test_store):
    store, sink = test_store
    a = store.scope("a").scope("b").counter("c")
    b = store.scope("a.b").counter("c")
    assert a is b  # same full name -> same stat (per-rule stats rely on this)
    a.inc()
    store.flush()
    assert sink.counters == {"a.b.c": 1}


def test_gauge_and_generator(test_store):
    store, sink = test_store
    g = store.gauge("pool.cx_active")

    class Gen:
        def generate_stats(self):
            g.set(42)

    store.add_stat_generator(Gen())
    store.flush()
    assert sink.gauges["pool.cx_active"] == 42


def test_statsd_sink_format():
    sent = []

    sink = StatsdSink("localhost", 0, prefix="ratelimit")
    sink._send = sent.append  # type: ignore
    sink.flush_counter("x.y", 3)
    sink.flush_gauge("g", 7)
    sink.flush_timer("t", 1.5)
    sink.flush()
    assert sent == [b"ratelimit.x.y:3|c\nratelimit.g:7|g\nratelimit.t:1.5|ms"]
