"""Unit tests for the stats pipeline."""

import threading
import time

from api_ratelimit_tpu.stats import (
    Histogram,
    StatsdSink,
    Store,
    TestSink,
    Timer,
    format_statsd_ms,
    render_prometheus,
)


def test_counter_flush_delta(test_store):
    store, sink = test_store
    c = store.scope("ratelimit").counter("hits")
    c.add(5)
    c.inc()
    store.flush()
    assert sink.counters == {"ratelimit.hits": 6}
    # second flush with no activity emits nothing new
    store.flush()
    assert sink.counters == {"ratelimit.hits": 6}
    c.inc()
    store.flush()
    assert sink.counters == {"ratelimit.hits": 7}


def test_scope_nesting_and_caching(test_store):
    store, sink = test_store
    a = store.scope("a").scope("b").counter("c")
    b = store.scope("a.b").counter("c")
    assert a is b  # same full name -> same stat (per-rule stats rely on this)
    a.inc()
    store.flush()
    assert sink.counters == {"a.b.c": 1}


def test_gauge_and_generator(test_store):
    store, sink = test_store
    g = store.gauge("pool.cx_active")

    class Gen:
        def generate_stats(self):
            g.set(42)

    store.add_stat_generator(Gen())
    store.flush()
    assert sink.gauges["pool.cx_active"] == 42


def test_statsd_sink_format():
    sent = []

    sink = StatsdSink("localhost", 0, prefix="ratelimit")
    sink._send = sent.append  # type: ignore
    sink.flush_counter("x.y", 3)
    sink.flush_gauge("g", 7)
    sink.flush_timer("t", 1.5)
    sink.flush()
    assert sent == [b"ratelimit.x.y:3|c\nratelimit.g:7|g\nratelimit.t:1.5|ms"]


def test_statsd_timer_fixed_point_not_exponential():
    """{:g} emitted `1e-05` for sub-microsecond timings, which statsd line
    parsers reject — values must stay fixed-point at any magnitude."""
    sent = []
    sink = StatsdSink("localhost", 0)
    sink._send = sent.append  # type: ignore
    sink.flush_timer("t", 1e-05)
    sink.flush_timer("t", 0.0)
    sink.flush_timer("t", 12345.678)
    sink.flush()
    lines = sent[0].decode().splitlines()
    assert lines == ["t:0.00001|ms", "t:0|ms", "t:12345.678|ms"]
    assert all("e" not in l.split(":")[1] for l in lines)
    assert format_statsd_ms(2.5e-07) == "0.00000025"


class TestTimerCap:
    def test_samples_capped_and_drops_counted(self):
        t = Timer("t")
        for i in range(Timer.MAX_SAMPLES + 100):
            t.add_value_ms(1.0)
        assert len(t._samples) == Timer.MAX_SAMPLES
        assert t.dropped() == 100
        assert t.count() == Timer.MAX_SAMPLES + 100
        # latch drains the buffer and recording resumes without drops
        assert len(t.latch()) == Timer.MAX_SAMPLES
        t.add_value_ms(2.0)
        assert t.dropped() == 100
        assert len(t._samples) == 1

    def test_store_flush_reports_dropped_timer_summary(self, test_store):
        store, sink = test_store
        t = store.timer("lat")
        t.add_value_ms(3.0)
        snap = store.debug_snapshot()
        assert snap["lat.count"] == 1
        assert snap["lat.p50_ms"] == 3.0
        assert snap["lat.p99_ms"] == 3.0


class TestHistogram:
    def test_bucketing_and_percentiles(self):
        h = Histogram("h", boundaries=(1.0, 2.0, 4.0))
        for v in (0.5, 1.5, 1.5, 3.0, 100.0):
            h.record(v)
        snap = h.snapshot()
        assert snap["counts"] == [1, 2, 1, 1]  # (-inf,1],(1,2],(2,4],overflow
        assert snap["count"] == 5
        assert snap["sum"] == 106.5
        assert 0 < snap["p50"] <= 2.0
        assert snap["p99"] == 4.0  # overflow clamps to the last edge
        assert h.percentile(0.5) == snap["p50"]

    def test_exemplar_only_in_top_bucket(self):
        h = Histogram("h", boundaries=(1.0, 10.0))
        h.record(0.5, exemplar="fast-trace")
        assert "exemplar" not in h.snapshot()
        assert not h.is_slow(10.0)
        assert h.is_slow(50.0)
        h.record(50.0, exemplar="slow-trace")
        ex = h.snapshot()["exemplar"]
        assert ex["trace_id"] == "slow-trace"
        assert ex["value"] == 50.0

    def test_store_registration_cached_and_in_snapshot(self, test_store):
        store, _ = test_store
        a = store.scope("svc").histogram("lat_ms", boundaries=(1.0, 5.0))
        b = store.scope("svc").histogram("lat_ms")
        assert a is b  # first registration pins boundaries
        a.record(0.5)
        a.record(50.0)
        snap = store.debug_snapshot()
        assert snap["svc.lat_ms.count"] == 2
        assert snap["svc.lat_ms.p99"] == 5.0

    def test_recording_under_threads_loses_nothing(self):
        h = Histogram("h", boundaries=(0.5, 1.0, 2.0, 4.0))
        n_threads, per_thread = 8, 5000

        def worker(tid):
            for i in range(per_thread):
                h.record((i % 40) / 8.0)

        threads = [
            threading.Thread(target=worker, args=(t,)) for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = h.snapshot()
        assert snap["count"] == n_threads * per_thread
        assert sum(snap["counts"]) == n_threads * per_thread

    def test_recording_is_cheap(self):
        """The <5% telemetry budget starts here: one record must stay in
        the microsecond range (loose bound — this catches a lock or
        allocation regression, not scheduler noise)."""
        h = Histogram("h")
        t0 = time.perf_counter()
        for i in range(100_000):
            h.record(1.25)
        per_record = (time.perf_counter() - t0) / 100_000
        assert per_record < 50e-6, f"record() cost {per_record * 1e6:.1f}us"


class TestStoreConcurrency:
    def test_flush_loop_start_stop_idempotent(self, test_store):
        store, _ = test_store
        store.start_flushing(interval_seconds=0.01)
        first = store._flush_thread
        store.start_flushing(interval_seconds=0.01)  # no second thread
        assert store._flush_thread is first
        store.stop_flushing()
        assert store._flush_thread is None
        store.stop_flushing()  # double stop is a no-op
        # restart works after stop
        store.start_flushing(interval_seconds=0.01)
        assert store._flush_thread is not None and store._flush_thread.is_alive()
        store.stop_flushing()

    def test_registration_races_flush(self, test_store):
        """Registering new stats while the flush loop runs must not skip,
        duplicate, or crash — the reg lock covers the registry snapshot."""
        store, sink = test_store
        store.start_flushing(interval_seconds=0.001)
        errors = []

        def register(tid):
            try:
                for i in range(200):
                    store.counter(f"c.{tid}.{i}").inc()
                    store.gauge(f"g.{tid}.{i}").set(i)
                    store.histogram(f"h.{tid}.{i}").record(1.0)
            except Exception as e:  # pragma: no cover - the assertion
                errors.append(e)

        threads = [
            threading.Thread(target=register, args=(t,)) for t in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        store.stop_flushing()
        store.flush()  # final flush drains everything registered
        assert not errors
        assert len(sink.counters) == 4 * 200
        assert all(v == 1 for v in sink.counters.values())


def test_prometheus_render_roundtrip(test_store):
    store, _ = test_store
    store.scope("ratelimit").counter("hits").add(3)
    store.gauge("depth").set(7)
    t = store.timer("old_t")
    t.add_value_ms(2.0)
    h = store.histogram("lat_ms", boundaries=(1.0, 5.0))
    h.record(0.5)
    h.record(99.0)
    text = render_prometheus(store)
    lines = text.strip().splitlines()
    # every line is either a TYPE comment or a parseable sample
    import re

    sample = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9]+(\.[0-9]+)?$"
    )
    comment = re.compile(
        r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (counter|gauge|histogram|summary)$"
    )
    for line in lines:
        assert sample.match(line) or comment.match(line), line
    assert "ratelimit_hits 3" in lines
    assert "depth 7" in lines
    assert 'lat_ms_bucket{le="1"} 1' in lines
    assert 'lat_ms_bucket{le="+Inf"} 2' in lines
    assert "lat_ms_count 2" in lines
    assert "old_t_count 1" in lines
