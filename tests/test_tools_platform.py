"""The measurement tools must honor JAX_PLATFORMS.

A site package force-sets jax_platforms=axon at import, overriding the
operator's env var; a tool that skips respect_jax_platforms_env() then
hangs trying to claim the (frequently down) device tunnel even when the
operator pinned JAX_PLATFORMS=cpu. That cost real debugging time on
2026-07-31 — pin it for every standalone measurement tool.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_tool(mod, extra=()):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # single CPU device, like an operator shell
    return subprocess.run(
        [sys.executable, "-m", mod, *extra],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=420,
    )


@pytest.mark.slow
@pytest.mark.parametrize(
    "mod,extra",
    [
        ("tools.linkprobe", ()),
        ("tools.divtest", ("--batch", "4096", "--repeats", "2")),
    ],
)
def test_tool_runs_on_cpu_when_pinned(mod, extra):
    proc = _run_tool(mod, extra)
    assert proc.returncode == 0, proc.stderr[-500:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
    assert lines, proc.stdout[-300:]
    assert json.loads(lines[-1])["platform"] == "cpu"


class TestJourneyReport:
    """tools/journey_report.py smoke (tier-1, jax-free): it must render a
    /debug/journeys capture into the per-stage table and --json form."""

    def _sample_doc(self):
        base = 1_000_000_000
        journeys = []
        for i, (dur, flags) in enumerate(
            [(12.0, ["slow"]), (3.0, ["over_limit"]), (40.0, ["fault", "slow"])]
        ):
            journeys.append(
                {
                    "kind": "request",
                    "trace_id": f"{i + 1:032x}",
                    "flags": flags,
                    "duration_ms": dur,
                    "start_ns": base,
                    "stages": {
                        "publish": base + 100_000,
                        "take": base + 400_000,
                        "pack": base + 450_000,
                        "launch": base + 900_000,
                        "redeem": base + int(dur * 1e6),
                        "scatter": base + int(dur * 1e6) + 50_000,
                    },
                    "thread": f"worker-{i}",
                }
            )
        return {"enabled": True, "live_p99_ms": 38.5, "retained": journeys}

    def _write_doc(self, tmp_path):
        import json

        path = tmp_path / "journeys.json"
        path.write_text(json.dumps(self._sample_doc()))
        return str(path)

    def test_text_report(self, tmp_path):
        proc = _run_tool(
            "tools.journey_report", (self._write_doc(tmp_path), "--top", "2")
        )
        assert proc.returncode == 0, proc.stderr[-500:]
        out = proc.stdout
        assert "[journeys] retained=3" in out
        for stage in ("publish", "take", "pack", "launch", "redeem", "scatter"):
            assert stage in out
        assert "top 2 slowest" in out
        assert "fault,slow" in out  # slowest journey's flags render

    def test_json_report(self, tmp_path):
        import json

        proc = _run_tool(
            "tools.journey_report", (self._write_doc(tmp_path), "--json")
        )
        assert proc.returncode == 0, proc.stderr[-500:]
        report = json.loads(proc.stdout)
        assert report["journeys"] == 3
        assert report["stages"]["publish"]["count"] == 3
        # slowest first, with per-stage ms deltas
        assert report["slowest"][0]["duration_ms"] == 40.0
        assert report["slowest"][0]["stage_ms"]["take"] > 0

    def test_bad_input_exits_nonzero(self, tmp_path):
        bad = tmp_path / "nope.json"
        bad.write_text("{not json")
        proc = _run_tool("tools.journey_report", (str(bad),))
        assert proc.returncode == 1
        assert "cannot read" in proc.stderr


class TestHotpathProfile:
    """tools/hotpath_profile.py smoke (tier-1, not slow): it must run the
    flat_per_second loop under cProfile and emit a parseable table."""

    def test_runs_and_parses(self):
        proc = _run_tool(
            "tools.hotpath_profile", ("-n", "120", "--top", "6")
        )
        assert proc.returncode == 0, proc.stderr[-500:]
        lines = proc.stdout.splitlines()
        summary = [ln for ln in lines if ln.startswith("[hotpath] rate=")]
        assert summary, proc.stdout[-300:]
        # summary parses: rate=<int>/s requests=<int>
        rate_field = summary[0].split()[1]
        assert rate_field.startswith("rate=") and rate_field.endswith("/s")
        assert int(rate_field[len("rate="):-len("/s")]) > 0
        header = [ln for ln in lines if "ncalls" in ln and "tottime" in ln]
        assert header, "pstats table header missing"
        # at least one profiled row mentions the service hot path
        assert any("should_rate_limit" in ln for ln in lines)

    def test_legacy_arm_runs(self):
        proc = _run_tool(
            "tools.hotpath_profile", ("-n", "60", "--top", "4", "--legacy")
        )
        assert proc.returncode == 0, proc.stderr[-500:]
        assert "path=legacy" in proc.stdout

    def test_slab_split_baseline(self):
        proc = _run_tool("tools.hotpath_profile", ("--slab-split",))
        assert proc.returncode == 0, proc.stderr[-500:]
        lines = proc.stdout.splitlines()
        summary = [ln for ln in lines if ln.startswith("[slab_split] batch=")]
        assert summary, proc.stdout[-300:]
        assert int(summary[0].split("batch=")[1]) > 0
        for stage in ("gather_ns", "scan_ns", "scatter_ns"):
            rows = [ln for ln in lines if ln.strip().startswith(stage)]
            assert rows, (stage, proc.stdout[-300:])
            assert "p50=" in rows[0] and "p99=" in rows[0]

    def test_shard_split_stage_table(self):
        """--shard-split forces its own virtual mesh (the harness strips
        XLA_FLAGS, so the tool must set the device split itself before
        jax initializes) and prints the routed owner's stage table."""
        proc = _run_tool(
            "tools.hotpath_profile", ("--shard-split", "--shards", "2")
        )
        assert proc.returncode == 0, proc.stderr[-500:]
        lines = proc.stdout.splitlines()
        summary = [ln for ln in lines if ln.startswith("[shard_split] shards=")]
        assert summary, proc.stdout[-300:]
        assert "shards=2" in summary[0] and "launches=" in summary[0]
        for stage in ("bucket_ns", "pad_ns", "launch_ns"):
            rows = [ln for ln in lines if ln.strip().startswith(stage)]
            assert rows, (stage, proc.stdout[-300:])
            assert "p50=" in rows[0] and "p99=" in rows[0]
        assert any(ln.strip().startswith("shard_rows") for ln in lines)
        assert any("padding_waste_pct=" in ln for ln in lines)

    def test_dispatch_arm_profiles_owner_thread(self):
        proc = _run_tool(
            "tools.hotpath_profile", ("-n", "120", "--top", "8", "--dispatch")
        )
        assert proc.returncode == 0, proc.stderr[-500:]
        assert "path=dispatch-owner" in proc.stdout
        lines = proc.stdout.splitlines()
        header = [ln for ln in lines if "ncalls" in ln and "tottime" in ln]
        assert header, "pstats table header missing"
        # the profiled thread is the OWNER loop, not the request thread
        assert any("dispatch.py" in ln and "_run" in ln for ln in lines)

    def test_frontend_arm_reports_native_split(self):
        """--frontend: one worker's decode→match→compose→publish loop
        over shm rings to a local owner, with the [native_split] line
        naming which stages ran native."""
        proc = _run_tool(
            "tools.hotpath_profile", ("-n", "120", "--top", "8", "--frontend")
        )
        assert proc.returncode == 0, proc.stderr[-500:]
        assert "path=frontend-shm" in proc.stdout
        lines = proc.stdout.splitlines()
        split = [ln for ln in lines if ln.startswith("[native_split]")]
        assert split, proc.stdout[-300:]
        # with the toolchain baked into this image the whole loop is
        # native end to end: codec + matcher + shm submit
        assert "codec=native" in split[0]
        assert "matcher=native" in split[0]
        assert "submit=shm" in split[0]
        header = [ln for ln in lines if "ncalls" in ln and "tottime" in ln]
        assert header, "pstats table header missing"
        assert any("shm_ring.py" in ln for ln in lines)
