"""The measurement tools must honor JAX_PLATFORMS.

A site package force-sets jax_platforms=axon at import, overriding the
operator's env var; a tool that skips respect_jax_platforms_env() then
hangs trying to claim the (frequently down) device tunnel even when the
operator pinned JAX_PLATFORMS=cpu. That cost real debugging time on
2026-07-31 — pin it for every standalone measurement tool.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_tool(mod, extra=()):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # single CPU device, like an operator shell
    return subprocess.run(
        [sys.executable, "-m", mod, *extra],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=420,
    )


@pytest.mark.slow
@pytest.mark.parametrize(
    "mod,extra",
    [
        ("tools.linkprobe", ()),
        ("tools.divtest", ("--batch", "4096", "--repeats", "2")),
    ],
)
def test_tool_runs_on_cpu_when_pinned(mod, extra):
    proc = _run_tool(mod, extra)
    assert proc.returncode == 0, proc.stderr[-500:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
    assert lines, proc.stdout[-300:]
    assert json.loads(lines[-1])["platform"] == "cpu"


class TestHotpathProfile:
    """tools/hotpath_profile.py smoke (tier-1, not slow): it must run the
    flat_per_second loop under cProfile and emit a parseable table."""

    def test_runs_and_parses(self):
        proc = _run_tool(
            "tools.hotpath_profile", ("-n", "120", "--top", "6")
        )
        assert proc.returncode == 0, proc.stderr[-500:]
        lines = proc.stdout.splitlines()
        summary = [ln for ln in lines if ln.startswith("[hotpath] rate=")]
        assert summary, proc.stdout[-300:]
        # summary parses: rate=<int>/s requests=<int>
        rate_field = summary[0].split()[1]
        assert rate_field.startswith("rate=") and rate_field.endswith("/s")
        assert int(rate_field[len("rate="):-len("/s")]) > 0
        header = [ln for ln in lines if "ncalls" in ln and "tottime" in ln]
        assert header, "pstats table header missing"
        # at least one profiled row mentions the service hot path
        assert any("should_rate_limit" in ln for ln in lines)

    def test_legacy_arm_runs(self):
        proc = _run_tool(
            "tools.hotpath_profile", ("-n", "60", "--top", "4", "--legacy")
        )
        assert proc.returncode == 0, proc.stderr[-500:]
        assert "path=legacy" in proc.stdout

    def test_dispatch_arm_profiles_owner_thread(self):
        proc = _run_tool(
            "tools.hotpath_profile", ("-n", "120", "--top", "8", "--dispatch")
        )
        assert proc.returncode == 0, proc.stderr[-500:]
        assert "path=dispatch-owner" in proc.stdout
        lines = proc.stdout.splitlines()
        header = [ln for ln in lines if "ncalls" in ln and "tottime" in ln]
        assert header, "pstats table header missing"
        # the profiled thread is the OWNER loop, not the request thread
        assert any("dispatch.py" in ln and "_run" in ln for ln in lines)
