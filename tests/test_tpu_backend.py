"""TPU backend differential tests vs the memory oracle, plus micro-batcher
behavior. Runs on the virtual CPU mesh; the same flows execute on real TPU
via bench.py / verify scripts."""

import random
import threading
import time

import pytest

from api_ratelimit_tpu.backends import MemoryRateLimitCache
from api_ratelimit_tpu.backends.batcher import MicroBatcher
from api_ratelimit_tpu.backends.tpu import TpuRateLimitCache
from api_ratelimit_tpu.limiter import BaseRateLimiter, LocalCache
from api_ratelimit_tpu.models import Code, Descriptor, RateLimitRequest, Unit
from api_ratelimit_tpu.models.config import RateLimit, new_rate_limit_stats
from api_ratelimit_tpu.models.response import RateLimitValue
from api_ratelimit_tpu.stats import Store, TestSink
from api_ratelimit_tpu.utils import FakeTimeSource


def make_limit(store, rpu, unit, key):
    return RateLimit(
        full_key=key,
        stats=new_rate_limit_stats(store, key),
        limit=RateLimitValue(requests_per_unit=rpu, unit=unit),
    )


def req(*pairs, hits=1, domain="domain"):
    return RateLimitRequest(
        domain=domain,
        descriptors=tuple(Descriptor.of(p) for p in pairs),
        hits_addend=hits,
    )


def make_tpu_cache(ts, local_cache_size=0, window=0.0, n_slots=1 << 12):
    local = LocalCache(local_cache_size, ts) if local_cache_size else None
    base = BaseRateLimiter(ts, local_cache=local, near_limit_ratio=0.8)
    return TpuRateLimitCache(
        base,
        n_slots=n_slots,
        batch_window_seconds=window,
        buckets=(128, 1024),
        max_batch=1024,
        use_pallas=False,
    )


class TestTpuBackend:
    def test_basic_over_limit_sequence(self):
        ts = FakeTimeSource(1_000_000)
        store = Store(TestSink())
        cache = make_tpu_cache(ts)
        limit = make_limit(store, 3, Unit.MINUTE, "k_v")
        for want in [Code.OK, Code.OK, Code.OK, Code.OVER_LIMIT]:
            resp = cache.do_limit(req(("k", "v")), [limit])
            assert resp.descriptor_statuses[0].code == want
        status = resp.descriptor_statuses[0]
        assert status.limit_remaining == 0
        assert status.duration_until_reset == 60 - 1_000_000 % 60
        assert limit.stats.total_hits.value() == 4
        assert limit.stats.over_limit.value() == 1

    def test_differential_vs_memory_oracle(self):
        """Randomized request stream: codes, remaining, throttle, and stats
        must match the Redis-semantics oracle exactly (no collisions at this
        scale)."""
        rng = random.Random(11)
        ts_a, ts_b = FakeTimeSource(500_000), FakeTimeSource(500_000)
        store_a, store_b = Store(TestSink()), Store(TestSink())
        tpu = make_tpu_cache(ts_a)
        mem = MemoryRateLimitCache(BaseRateLimiter(ts_b, near_limit_ratio=0.8))

        descriptors = [("api", str(i)) for i in range(12)]
        units = [Unit.SECOND, Unit.MINUTE, Unit.HOUR]
        limits_a = {}
        limits_b = {}
        for i, d in enumerate(descriptors):
            unit = units[i % 3]
            rpu = rng.randrange(2, 12)
            limits_a[d] = make_limit(store_a, rpu, unit, f"api_{i}")
            limits_b[d] = make_limit(store_b, rpu, unit, f"api_{i}")

        for step in range(300):
            if rng.random() < 0.2:
                ts_a.advance(1)
                ts_b.advance(1)
            chosen = rng.sample(descriptors, k=rng.randrange(1, 4))
            hits = rng.randrange(1, 3)
            request = req(*chosen, hits=hits)
            ra = tpu.do_limit(request, [limits_a[d] for d in chosen])
            rb = mem.do_limit(request, [limits_b[d] for d in chosen])
            assert ra.throttle_millis == rb.throttle_millis, f"step {step}"
            for i, (sa, sb) in enumerate(
                zip(ra.descriptor_statuses, rb.descriptor_statuses)
            ):
                assert sa.code == sb.code, f"step {step} desc {i}"
                assert sa.limit_remaining == sb.limit_remaining, f"step {step} desc {i}"
                assert sa.duration_until_reset == sb.duration_until_reset

        for i, d in enumerate(descriptors):
            la, lb = limits_a[d], limits_b[d]
            assert la.stats.total_hits.value() == lb.stats.total_hits.value()
            assert la.stats.over_limit.value() == lb.stats.over_limit.value(), i
            assert la.stats.near_limit.value() == lb.stats.near_limit.value(), i

    def test_local_cache_short_circuits_device(self):
        ts = FakeTimeSource(1_000_000)
        store = Store(TestSink())
        cache = make_tpu_cache(ts, local_cache_size=64)
        limit = make_limit(store, 2, Unit.HOUR, "k_v")
        request = req(("k", "v"))
        for _ in range(3):
            resp = cache.do_limit(request, [limit])
        assert resp.descriptor_statuses[0].code == Code.OVER_LIMIT
        launches_before = cache._engine_core._state.count is not None  # state handle

        # next over-limit request must come from the local cache: the slab
        # count stays at 3
        import numpy as np

        count_sum_before = int(np.asarray(cache._engine_core._state.count).sum())
        resp = cache.do_limit(request, [limit])
        assert resp.descriptor_statuses[0].code == Code.OVER_LIMIT
        assert int(np.asarray(cache._engine_core._state.count).sum()) == count_sum_before
        assert limit.stats.over_limit_with_local_cache.value() == 1

    def test_unchecked_descriptor(self):
        ts = FakeTimeSource(1_000_000)
        store = Store(TestSink())
        cache = make_tpu_cache(ts)
        limit = make_limit(store, 5, Unit.SECOND, "k_v")
        resp = cache.do_limit(req(("nolimit", "x"), ("k", "v")), [None, limit])
        assert resp.descriptor_statuses[0].code == Code.OK
        assert resp.descriptor_statuses[0].current_limit is None
        assert resp.descriptor_statuses[1].current_limit is not None

    def test_windowed_batching_coalesces_concurrent_requests(self):
        ts = FakeTimeSource(1_000_000)
        store = Store(TestSink())
        cache = make_tpu_cache(ts, window=0.02)
        limit = make_limit(store, 100, Unit.MINUTE, "k_v")

        results = []
        def worker():
            resp = cache.do_limit(req(("k", "v")), [limit])
            results.append(resp.descriptor_statuses[0])

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        cache.flush()
        assert len(results) == 8
        # all 8 hits serialized against one counter
        remainings = sorted(s.limit_remaining for s in results)
        assert remainings == [92, 93, 94, 95, 96, 97, 98, 99]
        cache.close()


class TestExactSlabOps:
    """The §4.4 analog of the reference's exact-wire-command assertions
    (test/redis/fixed_cache_impl_test.go:59-64 pins `INCRBY key hits` +
    `EXPIRE key ttl` verbatim): capture the exact row batch the backend
    submits to the device (the engine is block-native — the batcher's
    unit is a uint32[6, n] row block: fp_lo, fp_hi, hits, limit, divider,
    jitter)."""

    @staticmethod
    def _rows(blocks):
        """Decode captured row blocks into per-item operand tuples
        (fp, hits, limit, divider, jitter)."""
        import numpy as np

        out = []
        for block in blocks:
            for lo, hi, hits, limit, divider, jitter in np.asarray(block).T.tolist():
                out.append(((hi << 32) | lo, hits, limit, divider, jitter))
        return out

    def test_exact_items_submitted(self, test_store):
        from api_ratelimit_tpu.ops.hashing import fingerprint64

        store, _ = test_store
        ts = FakeTimeSource(1234)
        cache = make_tpu_cache(ts)
        captured = []
        real_execute = cache._batcher._execute

        def spy(blocks):
            captured.append(self._rows(blocks))
            return real_execute(blocks)

        cache._batcher._execute = spy
        limits = [
            make_limit(store.scope("t"), 10, Unit.MINUTE, "k1"),
            None,  # unchecked: must not reach the device
            make_limit(store.scope("t"), 7, Unit.SECOND, "k3"),
        ]
        request = req(("k1", "a"), ("k2", "b"), ("k3", "c"), hits=2)
        cache.do_limit(request, limits)
        cache.close()

        (batch,) = captured
        assert len(batch) == 2  # nil-limit descriptor filtered out
        it1, it3 = batch
        # INCRBY-analog operands, pinned exactly
        assert it1[0] == fingerprint64("domain", request.descriptors[0].entries, 60)
        assert it1[1:4] == (2, 10, 60)
        assert it3[0] == fingerprint64("domain", request.descriptors[2].entries, 1)
        assert it3[1:4] == (2, 7, 1)
        # EXPIRE-analog: no jitter configured => TTL exactly the unit window
        assert it1[4] == 0 and it3[4] == 0

    def test_jitter_rides_into_expiry(self, test_store):
        store, _ = test_store
        ts = FakeTimeSource(1234)
        base = BaseRateLimiter(
            ts,
            jitter_rand=random.Random(42),
            expiration_jitter_max_seconds=300,
        )
        cache = TpuRateLimitCache(
            base, n_slots=1 << 12, buckets=(128,), max_batch=128, use_pallas=False
        )
        captured = []
        real_execute = cache._batcher._execute
        cache._batcher._execute = lambda blocks: (
            captured.append(self._rows(blocks)),
            real_execute(blocks),
        )[1]
        limit = make_limit(store.scope("t"), 5, Unit.MINUTE, "k")
        cache.do_limit(req(("k", "v")), [limit])
        cache.close()
        (batch,) = captured
        # jittered TTL = unit + rand(max) (fixed_cache_impl.go:69-72);
        # seeded rand pins the exact value
        want = random.Random(42).randrange(300)
        assert batch[0][4] == want


class TestMicroBatcher:
    def test_direct_mode(self):
        calls = []
        b = MicroBatcher(lambda items: (calls.append(len(items)), items)[1])
        assert b.submit([1, 2, 3]) == [1, 2, 3]
        assert calls == [3]

    def test_windowed_coalescing_and_order(self):
        batches = []

        def execute(items):
            batches.append(list(items))
            return [x * 10 for x in items]

        b = MicroBatcher(execute, window_seconds=0.05, max_batch=100)
        out = []
        threads = [
            threading.Thread(target=lambda i=i: out.append((i, b.submit([i]))))
            for i in range(5)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        b.close()
        assert sorted(x for _, [x] in out) == [0, 10, 20, 30, 40]
        # coalesced into fewer launches than submissions
        assert len(batches) < 5

    def test_oversized_request_taken_alone(self):
        sizes = []

        def execute(items):
            sizes.append(len(items))
            return items

        b = MicroBatcher(execute, window_seconds=0.01, max_batch=4)
        res = b.submit(list(range(10)))
        assert res == list(range(10))
        assert sizes == [10]
        b.close()

    def test_warm_pipeline_skips_linger(self):
        # items enqueued while a batch is executing launch immediately after
        # it, without waiting the window again
        import time as _time

        executing = threading.Event()
        release = threading.Event()

        def execute(items):
            executing.set()
            release.wait(2.0)
            release.clear()
            return items

        b = MicroBatcher(execute, window_seconds=0.5, max_batch=100)
        t1 = threading.Thread(target=lambda: b.submit([1]))
        t1.start()
        assert executing.wait(2.0)  # batch 1 on device
        executing.clear()

        got = []
        t2 = threading.Thread(target=lambda: got.append(b.submit([2])))
        t2.start()
        # wait until item 2 is actually enqueued (mid-execute) — a fixed
        # sleep would flake under scheduler delay
        deadline = _time.monotonic() + 2.0
        while _time.monotonic() < deadline:
            with b._lock:
                if b._futures:
                    break
            _time.sleep(0.005)
        s = _time.monotonic()
        release.set()  # batch 1 finishes now
        assert executing.wait(2.0)  # batch 2 launched...
        launched_after = _time.monotonic() - s
        release.set()
        t1.join(2.0)
        t2.join(2.0)
        b.close()
        assert got == [[2]]
        # ...well inside the 0.5s window it would otherwise linger
        assert launched_after < 0.25, f"lingered {launched_after:.3f}s"

    def test_error_propagates_to_callers(self):
        def execute(items):
            raise RuntimeError("device on fire")

        b = MicroBatcher(execute, window_seconds=0.01, max_batch=4)
        with pytest.raises(RuntimeError, match="device on fire"):
            b.submit([1])
        b.close()


class TestMicroBatcherPipelined:
    """The double-buffered launch/collect mode (execute_launch +
    execute_collect): launches overlap the previous batch's readback."""

    @staticmethod
    def _make(launch_log, collect_log, collect_gate=None, max_inflight=2):
        def launch(items):
            launch_log.append(list(items))
            return list(items)

        def collect(token):
            if collect_gate is not None:
                collect_gate.wait(2.0)
            collect_log.append(list(token))
            return [x * 10 for x in token]

        return MicroBatcher(
            lambda items: [x * 10 for x in items],
            window_seconds=0.01,
            max_batch=4,
            execute_launch=launch,
            execute_collect=collect,
            max_inflight=max_inflight,
        )

    def test_results_and_order(self):
        launches, collects = [], []
        b = self._make(launches, collects)
        out = []
        threads = [
            threading.Thread(target=lambda i=i: out.append(b.submit([i])))
            for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        b.close()
        assert sorted(x for [x] in out) == [i * 10 for i in range(8)]
        # every launch collected exactly once; collect ORDER is caller-
        # driven (leader-collects), launch order is what sequences state
        assert sorted(launches) == sorted(collects)

    def test_launch_overlaps_collect(self):
        # while batch 1's collect is gated, batch 2's LAUNCH must happen —
        # that overlap is the whole point of the mode
        launches, collects = [], []
        gate = threading.Event()
        b = self._make(launches, collects, collect_gate=gate)
        t1 = threading.Thread(target=lambda: b.submit([1]))
        t1.start()
        deadline = time.monotonic() + 2.0
        while not launches and time.monotonic() < deadline:
            time.sleep(0.005)
        t2 = threading.Thread(target=lambda: b.submit([2]))
        t2.start()
        deadline = time.monotonic() + 2.0
        while len(launches) < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert len(launches) == 2, "launch 2 did not overlap collect 1"
        assert collects == []  # nothing collected yet: both in flight
        gate.set()
        t1.join(2.0)
        t2.join(2.0)
        b.close()
        assert sorted(collects) == [[1], [2]]  # order is caller-driven

    def test_close_with_collects_in_flight(self):
        # regression: close() while the bounded collect queue is full must
        # not deadlock (the _CLOSE put happens outside the dispatch lock)
        launches, collects = [], []
        gate = threading.Event()
        b = self._make(launches, collects, collect_gate=gate, max_inflight=1)
        results = []
        threads = [
            threading.Thread(target=lambda i=i: results.append(b.submit([i])))
            for i in range(3)
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 2.0
        while not launches and time.monotonic() < deadline:
            time.sleep(0.005)
        closer = threading.Thread(target=b.close)
        closer.start()
        gate.set()
        closer.join(5.0)
        assert not closer.is_alive(), "close() deadlocked"
        for t in threads:
            t.join(5.0)
        assert sorted(x for [x] in results) == [0, 10, 20]

    def test_collect_error_propagates(self):
        def launch(items):
            return list(items)

        def collect(token):
            raise RuntimeError("readback failed")

        b = MicroBatcher(
            lambda items: items,
            window_seconds=0.01,
            max_batch=4,
            execute_launch=launch,
            execute_collect=collect,
        )
        with pytest.raises(RuntimeError, match="readback failed"):
            b.submit([1])
        b.close()

    def test_flush_waits_for_collects(self):
        launches, collects = [], []
        gate = threading.Event()
        b = self._make(launches, collects, collect_gate=gate)
        t = threading.Thread(target=lambda: b.submit([7]))
        t.start()
        deadline = time.monotonic() + 2.0
        while not launches and time.monotonic() < deadline:
            time.sleep(0.005)
        flushed = threading.Event()
        f = threading.Thread(target=lambda: (b.flush(), flushed.set()))
        f.start()
        time.sleep(0.05)
        assert not flushed.is_set()  # collect still gated => not idle
        gate.set()
        f.join(2.0)
        assert flushed.is_set()
        t.join(2.0)
        b.close()


class TestBlockNativePath:
    """The sidecar server's block-native path (engine block_mode=True):
    uint32[6, n] wire blocks go straight to the padded device block with
    numpy row copies only — decision-identical to the per-item path, and
    coalescing across submitters is preserved."""

    @staticmethod
    def _items_and_block(n, seed=0, limit=100):
        import numpy as np

        from api_ratelimit_tpu.backends.tpu import _Item

        rng = np.random.RandomState(seed)
        fps = rng.randint(1, 1 << 62, size=n, dtype=np.int64)
        items = [
            _Item(fp=int(f), hits=1, limit=limit, divider=60, jitter=0)
            for f in fps
        ]
        block = np.zeros((6, n), dtype=np.uint32)
        block[0] = (fps.astype(np.uint64) & 0xFFFFFFFF).astype(np.uint32)
        block[1] = (fps.astype(np.uint64) >> np.uint64(32)).astype(np.uint32)
        block[2] = 1
        block[3] = limit
        block[4] = 60
        return items, block

    def test_block_matches_item_path(self):
        import numpy as np

        from api_ratelimit_tpu.backends.tpu import SlabDeviceEngine

        ts = FakeTimeSource(1000)
        item_eng = SlabDeviceEngine(
            time_source=ts, n_slots=1 << 12, use_pallas=False
        )
        block_eng = SlabDeviceEngine(
            time_source=ts, n_slots=1 << 12, use_pallas=False, block_mode=True
        )
        for seed in (0, 1, 0):  # distinct key sets, then counter continuation
            items, block = self._items_and_block(300, seed=seed)
            want = item_eng.submit(items)
            got = block_eng.submit_block(block)
            assert got.dtype == np.uint32
            assert want == got.tolist()
        item_eng.close()
        block_eng.close()

    def test_block_mode_guards_verbs(self):
        from api_ratelimit_tpu.backends.tpu import SlabDeviceEngine

        ts = FakeTimeSource(1000)
        block_eng = SlabDeviceEngine(
            time_source=ts, n_slots=1 << 12, use_pallas=False, block_mode=True
        )
        item_eng = SlabDeviceEngine(time_source=ts, n_slots=1 << 12, use_pallas=False)
        items, block = self._items_and_block(4)
        with pytest.raises(RuntimeError, match="block_mode"):
            block_eng.submit(items)
        with pytest.raises(RuntimeError, match="block_mode"):
            item_eng.submit_block(block)
        block_eng.close()
        item_eng.close()

    def test_windowed_block_coalescing(self):
        """Blocks from concurrent submitters coalesce into shared launches
        (the sidecar's aggregation claim), and each submitter gets exactly
        its own slice back."""
        import numpy as np
        from concurrent.futures import ThreadPoolExecutor

        from api_ratelimit_tpu.backends.tpu import SlabDeviceEngine

        ts = FakeTimeSource(1000)
        eng = SlabDeviceEngine(
            time_source=ts,
            n_slots=1 << 12,
            use_pallas=False,
            block_mode=True,
            batch_window_seconds=0.005,
        )
        # 4 submitters, disjoint key ranges, duplicate keys inside each
        def one(k):
            n = 64
            block = np.zeros((6, n), dtype=np.uint32)
            block[0] = np.arange(n, dtype=np.uint32) // 8 + 1000 * (k + 1)
            block[1] = k + 1
            block[2] = 1
            block[3] = 1_000_000
            block[4] = 60
            return eng.submit_block(block)

        with ThreadPoolExecutor(4) as ex:
            outs = list(ex.map(one, range(4)))
        for out in outs:
            # 8 duplicates per key serialize within the submitter's block:
            # counters 1..8 per key group regardless of coalescing
            assert out.tolist() == [i % 8 + 1 for i in range(64)]
        # coalescing happened: fewer launches than submitters is possible
        # but not guaranteed under timing; the hard invariant is the
        # decision count
        assert eng.health_snapshot()["decisions"] == 4 * 64
        eng.close()


class TestSlabHealthStats:
    def test_health_gauges_reach_stats_tree(self, test_store):
        from api_ratelimit_tpu.backends.tpu import SlabHealthStats
        from api_ratelimit_tpu.models import Descriptor, RateLimitRequest

        store, sink = test_store
        ts = FakeTimeSource(1000)
        cache = make_tpu_cache(ts)
        limit = make_limit(store.scope("r"), 10, Unit.MINUTE, "h_v")
        for i in range(4):
            cache.do_limit(
                RateLimitRequest(
                    domain="d", descriptors=(Descriptor.of(("h", f"v{i}")),)
                ),
                [limit],
            )
        snap = cache.engine.health_snapshot()
        assert snap["evictions_live"] == 0 and snap["drops"] == 0
        assert snap["evictions_expired"] == 0 and snap["evictions_window"] == 0
        assert snap["live_slots"] == 4
        assert 0 < snap["occupancy"] < 1
        # the alarm-gauge denominator: 4 decisions submitted, none lossy
        assert snap["decisions"] == 4
        assert snap["loss_ppm"] == 0

        store.add_stat_generator(
            SlabHealthStats(cache.engine, store.scope("ratelimit").scope("slab"))
        )
        store.flush()
        assert sink.gauges["ratelimit.slab.evictions.expired"] == 0
        assert sink.gauges["ratelimit.slab.evictions.window"] == 0
        assert sink.gauges["ratelimit.slab.evictions.live"] == 0
        assert sink.gauges["ratelimit.slab.drops"] == 0
        assert sink.gauges["ratelimit.slab.decisions"] == 4
        assert sink.gauges["ratelimit.slab.loss_ppm"] == 0
        assert sink.gauges["ratelimit.slab.live_slots"] == 4
        assert sink.gauges["ratelimit.slab.occupancy"] == int(4 / (1 << 12) * 1e6)
        cache.close()

    def test_pallas_failure_falls_back_to_xla(self):
        """ADVICE r4: use_pallas=True on a platform whose Mosaic rejects
        the kernel must degrade to the XLA twin at the first launch — not
        fail every request. CPU rejects non-interpret pallas at compile
        time, exercising the real error path; the retry runs on the still-
        intact donated state."""
        from api_ratelimit_tpu.backends.tpu import SlabDeviceEngine, _Item

        eng = SlabDeviceEngine(
            time_source=FakeTimeSource(1000), n_slots=1 << 12, use_pallas=True
        )
        out = eng._launch(
            [_Item(fp=123456789, hits=1, limit=10, divider=60, jitter=0)]
        )
        assert out == [1]
        assert eng._use_pallas is False  # permanent flip, no per-launch retry
        eng.close()

    def test_loss_ppm_ratio(self):
        """loss_ppm is the parity-erosion alarm (VERDICT r4 weak #3): it is
        the lossy-event RATE, so tripling drops at constant traffic triples
        the gauge — an absolute-counter dashboard can miss that."""
        from api_ratelimit_tpu.backends.tpu import _loss_ppm

        base = {"evictions_live": 10, "drops": 90, "decisions": 1_000_000}
        assert _loss_ppm(base) == 100
        tripled = dict(base, drops=270)
        assert _loss_ppm(tripled) == 280
        assert _loss_ppm(
            {"evictions_live": 0, "drops": 0, "decisions": 0}
        ) == 0


class TestReadbackWidths:
    """The per-launch readback cap picks the narrowest EXACT width
    (cap > limit + hits for every item, backends/tpu.py:_pack_with_cap).
    The differential fuzz only uses tiny limits, so the u16 and u32
    readback paths — and a mixed-width launch forcing promotion — are
    pinned here with exact counts across the u8 saturation boundary."""

    def test_u16_readback_exact_across_255(self):
        from api_ratelimit_tpu.backends.tpu import SlabDeviceEngine, _Item

        ts = FakeTimeSource(1000)
        eng = SlabDeviceEngine(time_source=ts, n_slots=1 << 10, use_pallas=False)
        try:
            item = _Item(fp=12345, hits=100, limit=300, divider=3600, jitter=0)
            afters = [eng.submit([item])[0] for _ in range(5)]
            # u8 would saturate at 255; the cap math must pick u16 and
            # return exact counts through and past the limit
            assert afters == [100, 200, 300, 400, 500]
        finally:
            eng.close()

    def test_u32_readback_exact_across_65535(self):
        from api_ratelimit_tpu.backends.tpu import SlabDeviceEngine, _Item

        ts = FakeTimeSource(2000)
        eng = SlabDeviceEngine(time_source=ts, n_slots=1 << 10, use_pallas=False)
        try:
            item = _Item(fp=777, hits=40000, limit=70000, divider=3600, jitter=0)
            afters = [eng.submit([item])[0] for _ in range(3)]
            assert afters == [40000, 80000, 120000]
        finally:
            eng.close()

    def test_mixed_width_launch_promotes_whole_launch(self):
        from api_ratelimit_tpu.backends.tpu import SlabDeviceEngine, _Item

        ts = FakeTimeSource(3000)
        eng = SlabDeviceEngine(time_source=ts, n_slots=1 << 10, use_pallas=False)
        try:
            small = _Item(fp=1, hits=1, limit=5, divider=3600, jitter=0)
            big = _Item(fp=2, hits=500, limit=70000, divider=3600, jitter=0)
            for expect_small, expect_big in ((1, 500), (2, 1000), (3, 1500)):
                got = eng.submit([small, big])
                assert got == [expect_small, expect_big]
        finally:
            eng.close()

