"""Tracing subsystem tests: span lifecycle, B3 propagation, env config,
gRPC/HTTP server spans, and the service/backend instrumentation points
(reference: src/tracing/, span usage in src/service/ratelimit.go and
src/redis/fixed_cache_impl.go)."""

from __future__ import annotations

import json
import socket
import threading
import time

import pytest

from api_ratelimit_tpu import tracing
from api_ratelimit_tpu.tracing import (
    CollectorTracer,
    NoopTracer,
    RecordingTracer,
    SpanContext,
    activate,
    active_span,
    extract,
    inject,
    reset_global_tracer,
    set_global_tracer,
    tracer_from_env,
)


@pytest.fixture(autouse=True)
def _clean_global_tracer():
    reset_global_tracer()
    yield
    reset_global_tracer()


class TestSpanLifecycle:
    def test_basic_span(self):
        tracer = RecordingTracer()
        span = tracer.start_span("op")
        span.set_tag("backend", "tpu")
        span.log_kv(event="DoLimit.start", limits_count=3)
        time.sleep(0.01)
        span.finish()
        (got,) = tracer.finished_spans()
        assert got.operation_name == "op"
        assert got.tags == {"backend": "tpu"}
        assert got.logs[0][1] == {"event": "DoLimit.start", "limits_count": 3}
        assert got.finish_time >= got.start_time
        # duration is the span's own elapsed time (monotonic), not a raw
        # clock reading: ~10ms here, never minutes of machine uptime
        assert 0.005 < got.duration < 5.0

    def test_child_span_shares_trace_id(self):
        tracer = RecordingTracer()
        parent = tracer.start_span("parent")
        child = tracer.start_span("child", child_of=parent)
        assert child.context.trace_id == parent.context.trace_id
        assert child.context.span_id != parent.context.span_id
        assert child.parent_id == parent.context.span_id

    def test_with_statement_finishes_and_marks_error(self):
        tracer = RecordingTracer()
        with pytest.raises(ValueError):
            with tracer.start_span("boom"):
                raise ValueError("nope")
        (got,) = tracer.finished_spans()
        assert got.tags["error"] is True
        assert any(f.get("event") == "error" for _, f in got.logs)

    def test_double_finish_records_once(self):
        tracer = RecordingTracer()
        span = tracer.start_span("op")
        span.finish()
        span.finish()
        assert len(tracer.finished_spans()) == 1

    def test_ring_bound(self):
        tracer = RecordingTracer(max_spans=4)
        for i in range(10):
            tracer.start_span(f"op{i}").finish()
        names = [s.operation_name for s in tracer.finished_spans()]
        assert names == ["op6", "op7", "op8", "op9"]

    def test_active_span_contextvar(self):
        tracer = RecordingTracer()
        assert active_span() is None
        with tracer.start_span("op") as span, activate(span):
            assert active_span() is span
        assert active_span() is None

    def test_unsampled_spans_not_recorded(self):
        # B3 sampled=0 must suppress recording/export of the whole trace
        tracer = RecordingTracer()
        parent_ctx = SpanContext(trace_id=5, span_id=6, sampled=False)
        with tracer.start_span("unsampled", child_of=parent_ctx):
            pass
        assert tracer.finished_spans() == []

    def test_noop_span_not_activated(self):
        # Disabled tracing must leave active_span() None on every transport
        span = NoopTracer().start_span("op")
        with activate(span):
            assert active_span() is None

    def test_noop_tracer_is_free(self):
        tracer = NoopTracer()
        span = tracer.start_span("op")
        span2 = tracer.start_span("other")
        assert span is span2  # shared singleton, no allocation
        span.set_tag("k", "v").log_kv(event="e").set_error(ValueError())
        span.finish()
        assert span.tags == {}
        assert span.logs == []


class TestB3Propagation:
    def test_roundtrip(self):
        ctx = SpanContext(trace_id=0xABC123, span_id=0xDEF456, sampled=True)
        carrier: dict[str, str] = {}
        inject(ctx, carrier)
        got = extract(carrier)
        assert got == ctx

    def test_extract_case_insensitive_and_64bit(self):
        got = extract(
            {"X-B3-TraceId": "00000000000000ab", "X-B3-SpanId": "00000000000000cd"}
        )
        assert got is not None
        assert got.trace_id == 0xAB
        assert got.span_id == 0xCD
        assert got.sampled is True  # absent header defaults to sampled

    def test_extract_sampled_zero(self):
        carrier = {}
        inject(SpanContext(trace_id=1, span_id=2, sampled=False), carrier)
        assert extract(carrier).sampled is False

    @pytest.mark.parametrize(
        "carrier",
        [
            {},
            {"x-b3-traceid": "zz", "x-b3-spanid": "0000000000000001"},
            {"x-b3-traceid": "abc", "x-b3-spanid": "0000000000000001"},
            {"x-b3-traceid": "0" * 32, "x-b3-spanid": "0" * 16},  # zero ids
            {"x-b3-traceid": "0" * 32},  # missing span id
        ],
    )
    def test_extract_invalid_returns_none(self, carrier):
        assert extract(carrier) is None

    def test_extract_from_tuples(self):
        # gRPC invocation_metadata shape: iterable of (key, value)
        meta = [("x-b3-traceid", "0" * 31 + "1"), ("x-b3-spanid", "0" * 15 + "2")]
        got = extract(meta)
        assert (got.trace_id, got.span_id) == (1, 2)


class TestEnvConfig:
    def test_disabled_by_default(self, monkeypatch):
        for var in (
            tracing.tracer.TRACING_ENABLED_ENV,
            tracing.tracer.LIGHTSTEP_ENABLED_ENV,
        ):
            monkeypatch.delenv(var, raising=False)
        assert isinstance(tracer_from_env(), NoopTracer)

    def test_enabled_without_collector_records(self, monkeypatch):
        monkeypatch.setenv(tracing.tracer.TRACING_ENABLED_ENV, "true")
        monkeypatch.delenv(tracing.tracer.TRACING_HOST_ENV, raising=False)
        monkeypatch.delenv(tracing.tracer.LIGHTSTEP_HOST_ENV, raising=False)
        assert isinstance(tracer_from_env(), RecordingTracer)

    def test_reference_lightstep_names_accepted(self, monkeypatch):
        monkeypatch.delenv(tracing.tracer.TRACING_ENABLED_ENV, raising=False)
        monkeypatch.setenv(tracing.tracer.LIGHTSTEP_ENABLED_ENV, "1")
        assert isinstance(tracer_from_env(), RecordingTracer)

    def test_bad_bool_raises(self, monkeypatch):
        monkeypatch.setenv(tracing.tracer.TRACING_ENABLED_ENV, "banana")
        with pytest.raises(ValueError):
            tracer_from_env()

    def test_enabled_with_collector(self, monkeypatch):
        monkeypatch.setenv(tracing.tracer.TRACING_ENABLED_ENV, "true")
        monkeypatch.setenv(tracing.tracer.TRACING_HOST_ENV, "localhost")
        monkeypatch.setenv(tracing.tracer.TRACING_PORT_ENV, "9999")
        tracer = tracer_from_env()
        try:
            assert isinstance(tracer, CollectorTracer)
        finally:
            tracer.close()


class TestCollectorExport:
    def test_spans_ship_as_json_lines(self):
        received: list[bytes] = []
        done = threading.Event()
        listener = socket.socket()
        listener.bind(("127.0.0.1", 0))
        listener.listen(1)
        port = listener.getsockname()[1]

        def accept():
            conn, _ = listener.accept()
            with conn:
                while chunk := conn.recv(65536):
                    received.append(chunk)
            done.set()

        threading.Thread(target=accept, daemon=True).start()
        tracer = CollectorTracer(
            "127.0.0.1", port, token="tok", flush_interval=0.05
        )
        with tracer.start_span("exported") as span:
            span.set_tag("backend", "tpu")
        tracer.close(timeout=2.0)
        listener.close()
        assert done.wait(2.0)
        lines = b"".join(received).decode().strip().splitlines()
        payload = json.loads(lines[0])
        assert payload["span"]["operation_name"] == "exported"
        assert payload["access_token"] == "tok"
        assert payload["component"] == "apigw-ratelimit"

    def test_unreachable_collector_drops_without_error(self):
        tracer = CollectorTracer("127.0.0.1", 1, flush_interval=0.05)
        tracer.start_span("dropped").finish()
        time.sleep(0.2)
        tracer.close(timeout=2.0)  # must not raise


class TestServiceInstrumentation:
    def _service(self, test_store, **kwargs):
        from api_ratelimit_tpu.backends.memory import MemoryRateLimitCache
        from api_ratelimit_tpu.limiter.base_limiter import BaseRateLimiter
        from api_ratelimit_tpu.service.ratelimit import RateLimitService
        from api_ratelimit_tpu.utils.timeutil import FakeTimeSource

        store, _sink = test_store

        class FakeRuntime:
            def snapshot(self):
                class Snap:
                    def keys(self):
                        return ["config.basic"]

                    def get(self, key):
                        return (
                            "domain: basic\n"
                            "descriptors:\n"
                            "  - key: k1\n"
                            "    rate_limit: {unit: second, requests_per_unit: 10}\n"
                        )

                return Snap()

            def add_update_callback(self, cb):
                pass

        ts = FakeTimeSource(1234)
        base = BaseRateLimiter(time_source=ts, jitter_rand=None)
        return RateLimitService(
            runtime=FakeRuntime(),
            cache=MemoryRateLimitCache(base),
            stats_scope=store.scope("ratelimit").scope("service"),
            time_source=ts,
            runtime_watch_root=True,
            **kwargs,
        )

    def test_worker_logs_and_backend_tag(self, test_store):
        from api_ratelimit_tpu.models.descriptors import (
            Descriptor,
            RateLimitRequest,
        )

        service = self._service(test_store)
        tracer = RecordingTracer()
        set_global_tracer(tracer)
        req = RateLimitRequest(
            domain="basic", descriptors=(Descriptor.of(("k1", "v1")),)
        )
        with tracer.start_span("rpc") as span, activate(span):
            service.should_rate_limit(req)
        (got,) = tracer.finished_spans()
        events = [f.get("event") for _, f in got.logs]
        assert "shouldRateLimitWorker.start" in events
        assert "shouldRateLimitWorker.done" in events
        assert got.tags.get("backend") == "memory"
        done = [
            f for _, f in got.logs if f.get("event") == "shouldRateLimitWorker.done"
        ]
        assert done[0]["response_code"] == 1  # Code.OK

    def test_error_marks_span(self, test_store):
        from api_ratelimit_tpu.models.descriptors import RateLimitRequest
        from api_ratelimit_tpu.service.ratelimit import ServiceError

        service = self._service(test_store)
        tracer = RecordingTracer()
        set_global_tracer(tracer)
        req = RateLimitRequest(domain="", descriptors=[])
        with pytest.raises(ServiceError):
            with tracer.start_span("rpc") as span, activate(span):
                service.should_rate_limit(req)
        (got,) = tracer.finished_spans()
        assert got.tags["error"] is True

    def test_sleep_on_throttle_child_span(self, test_store):
        from api_ratelimit_tpu.models.response import DoLimitResponse

        service = self._service(test_store, max_sleeping_routines=2)
        tracer = RecordingTracer()
        set_global_tracer(tracer)
        resp = DoLimitResponse()
        resp.throttle_millis = 250
        with tracer.start_span("rpc") as span, activate(span):
            service._maybe_sleep(resp)
        throttle = [
            s
            for s in tracer.finished_spans()
            if s.operation_name == "sleep_on_throttle"
        ]
        assert len(throttle) == 1
        assert throttle[0].tags["throttling.sleep_ms"] == 250
        assert throttle[0].parent_id == span.context.span_id
        assert resp.throttle_millis == 0  # server-side throttled: reset

    def test_sleep_semaphore_exhausted_tags_error(self, test_store):
        from api_ratelimit_tpu.models.response import DoLimitResponse

        service = self._service(test_store, max_sleeping_routines=1)
        # exhaust the semaphore so acquire(blocking=False) fails
        assert service._sleeper_semaphore.acquire(blocking=False)
        tracer = RecordingTracer()
        set_global_tracer(tracer)
        resp = DoLimitResponse()
        resp.throttle_millis = 250
        with tracer.start_span("rpc") as span, activate(span):
            service._maybe_sleep(resp)
        (throttle,) = [
            s
            for s in tracer.finished_spans()
            if s.operation_name == "sleep_on_throttle"
        ]
        assert throttle.tags.get("error") is True
        events = [f.get("event") for _, f in throttle.logs]
        assert "throttling.sem_exhausted" in events
        assert resp.throttle_millis == 250  # not throttled server-side


class TestDoLimitErrorTagAudit:
    """The backend do_limit spans must carry the error tag on exception
    paths (QueueFullError, DeadlineExceededError, CacheError) — not just
    success-path log events (the PR-7 span audit)."""

    def _tpu_cache(self, engine):
        from api_ratelimit_tpu.backends.tpu import TpuRateLimitCache
        from api_ratelimit_tpu.limiter.base_limiter import BaseRateLimiter
        from api_ratelimit_tpu.utils import FakeTimeSource

        base = BaseRateLimiter(FakeTimeSource(1_000_000), jitter_rand=None)
        return TpuRateLimitCache(base, engine=engine)

    def _request_and_limit(self, test_store):
        from api_ratelimit_tpu.models import (
            Descriptor,
            RateLimitRequest,
            Unit,
        )
        from api_ratelimit_tpu.models.config import (
            RateLimit,
            new_rate_limit_stats,
        )
        from api_ratelimit_tpu.models.response import RateLimitValue

        store, _ = test_store
        limit = RateLimit(
            full_key="k_v",
            stats=new_rate_limit_stats(store, "k_v"),
            limit=RateLimitValue(requests_per_unit=5, unit=Unit.MINUTE),
        )
        request = RateLimitRequest(
            domain="d", descriptors=(Descriptor.of(("k", "v")),)
        )
        return request, limit

    @pytest.mark.parametrize(
        "exc_type",
        ["QueueFullError", "DeadlineExceededError", "CacheError"],
    )
    def test_tpu_do_limit_exception_tags_error(self, test_store, exc_type):
        from api_ratelimit_tpu.backends.overload import QueueFullError
        from api_ratelimit_tpu.limiter.cache import (
            CacheError,
            DeadlineExceededError,
        )

        exc_cls = {
            "QueueFullError": QueueFullError,
            "DeadlineExceededError": DeadlineExceededError,
            "CacheError": CacheError,
        }[exc_type]

        class BoomEngine:
            def submit(self, items):
                raise exc_cls("boom")

            def flush(self):
                pass

            def close(self):
                pass

        cache = self._tpu_cache(BoomEngine())
        request, limit = self._request_and_limit(test_store)
        tracer = RecordingTracer()
        set_global_tracer(tracer)
        with pytest.raises(exc_cls):
            with tracer.start_span("rpc") as span, activate(span):
                cache.do_limit(request, [limit])
        (got,) = tracer.finished_spans()
        assert got.tags.get("error") is True
        assert got.tags.get("backend") == "tpu"
        assert any(f.get("event") == "error" for _, f in got.logs)

    def test_redis_do_limit_exception_tags_error(self, test_store):
        from api_ratelimit_tpu.backends.redis import RedisRateLimitCache
        from api_ratelimit_tpu.limiter.base_limiter import BaseRateLimiter
        from api_ratelimit_tpu.limiter.cache import CacheError
        from api_ratelimit_tpu.utils import FakeTimeSource

        class BoomClient:
            def pipe_do(self, cmds):
                raise CacheError("redis down")

        base = BaseRateLimiter(FakeTimeSource(1_000_000), jitter_rand=None)
        cache = RedisRateLimitCache(BoomClient(), base)
        request, limit = self._request_and_limit(test_store)
        tracer = RecordingTracer()
        set_global_tracer(tracer)
        with pytest.raises(CacheError):
            with tracer.start_span("rpc") as span, activate(span):
                cache.do_limit(request, [limit])
        (got,) = tracer.finished_spans()
        assert got.tags.get("error") is True
        assert got.tags.get("backend") == "redis"


class TestZipkinExport:
    """Spans must land at a real (local) zipkin-compatible HTTP collector
    as valid v2 JSON (VERDICT round 1: a wire exporter, not just the
    in-process ring buffer)."""

    def _collector(self):
        import http.server
        import json as json_mod
        import threading

        received = []

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                body = self.rfile.read(int(self.headers["Content-Length"]))
                received.append(
                    (self.path, dict(self.headers), json_mod.loads(body))
                )
                self.send_response(202)
                self.end_headers()

            def log_message(self, *a):
                pass

        server = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        return server, received

    def test_spans_posted_as_zipkin_v2(self):
        from api_ratelimit_tpu.tracing.tracer import ZipkinTracer

        server, received = self._collector()
        try:
            tracer = ZipkinTracer(
                f"http://127.0.0.1:{server.server_port}",
                token="tok",
                flush_interval=0.05,
            )
            parent = tracer.start_span("ShouldRateLimit", tags={"backend": "tpu"})
            child = tracer.start_span("DoLimit", child_of=parent)
            child.log_kv(event="lookup.start", batch_items=3)
            child.finish()
            parent.finish()
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and sum(
                len(batch) for _, _, batch in received
            ) < 2:
                time.sleep(0.02)
            tracer.close()
        finally:
            server.shutdown()

        spans = [s for _, _, batch in received for s in batch]
        assert len(spans) == 2
        path, headers, _ = received[0]
        assert path == "/api/v2/spans"
        assert headers.get("Authorization") == "Bearer tok"
        by_name = {s["name"]: s for s in spans}
        p, c = by_name["ShouldRateLimit"], by_name["DoLimit"]
        assert c["traceId"] == p["traceId"]
        assert c["parentId"] == p["id"]
        assert p["tags"]["backend"] == "tpu"
        assert p["localEndpoint"]["serviceName"]
        assert c["annotations"] and "lookup.start" in c["annotations"][0]["value"]
        assert p["duration"] >= 1 and isinstance(p["timestamp"], int)

    def test_collector_down_never_blocks_requests(self):
        from api_ratelimit_tpu.tracing.tracer import ZipkinTracer

        # nothing listening on the port: spans drop, request path unharmed
        tracer = ZipkinTracer("http://127.0.0.1:1", flush_interval=0.05)
        for _ in range(100):
            tracer.start_span("op").finish()
        time.sleep(0.2)
        tracer.close()

    def test_tracer_from_env_selects_zipkin(self, monkeypatch):
        from api_ratelimit_tpu.tracing import tracer as trc

        monkeypatch.setenv(trc.TRACING_ENABLED_ENV, "true")
        monkeypatch.setenv(trc.TRACING_ZIPKIN_URL_ENV, "http://localhost:9411")
        built = trc.tracer_from_env()
        assert isinstance(built, trc.ZipkinTracer)
        assert built._url == "http://localhost:9411/api/v2/spans"
        built.close()
