"""Unit tests for the transport edge: proto adapters (the conversion logic of
src/service/ratelimit_legacy.go:62-150 and the v3 edge), the runtime loader's
key convention + change detection, and the aux CLIs."""

import os
import sys
import time

import pytest

from api_ratelimit_tpu.models.descriptors import Descriptor, Entry, LimitOverride
from api_ratelimit_tpu.models.response import Code, DescriptorStatus, HeaderValue, RateLimitValue
from api_ratelimit_tpu.models.units import Unit
from api_ratelimit_tpu.pb import common_ratelimit_v3, rls_v2, rls_v3
from api_ratelimit_tpu.server import proto_adapter
from api_ratelimit_tpu.server.runtime_loader import DirectoryRuntimeLoader, scan_directory


class TestProtoAdapter:
    def test_request_from_v3_full(self):
        msg = rls_v3.RateLimitRequest(domain="d", hits_addend=7)
        d0 = msg.descriptors.add()
        d0.entries.add(key="k1", value="v1")
        d0.entries.add(key="k2", value="v2")
        d1 = msg.descriptors.add()
        d1.entries.add(key="k3", value="v3")
        d1.limit.requests_per_unit = 42
        d1.limit.unit = common_ratelimit_v3.HOUR

        req = proto_adapter.request_from_v3(msg)
        assert req.domain == "d"
        assert req.hits_addend == 7
        assert req.descriptors[0] == Descriptor(
            entries=(Entry("k1", "v1"), Entry("k2", "v2"))
        )
        assert req.descriptors[1].limit == LimitOverride(
            requests_per_unit=42, unit=Unit.HOUR
        )
        # absent override stays None (HasField, not default-instance)
        assert req.descriptors[0].limit is None

    def test_request_from_v2(self):
        msg = rls_v2.RateLimitRequest(domain="legacy", hits_addend=2)
        d = msg.descriptors.add()
        d.entries.add(key="k", value="v")
        req = proto_adapter.request_from_v2(msg)
        assert req.domain == "legacy"
        assert req.descriptors[0].entries == (Entry("k", "v"),)
        assert req.descriptors[0].limit is None

    def _statuses(self):
        return [
            DescriptorStatus(
                code=Code.OK,
                current_limit=RateLimitValue(10, Unit.MINUTE),
                limit_remaining=9,
                duration_until_reset=30,
            ),
            DescriptorStatus(code=Code.OVER_LIMIT, limit_remaining=0),
            DescriptorStatus(code=Code.OK),  # unmatched: no limit
        ]

    def test_response_to_v3(self):
        resp = proto_adapter.response_to_v3(
            Code.OVER_LIMIT,
            self._statuses(),
            [HeaderValue("x-ratelimit-throttle-ms", "250")],
        )
        assert resp.overall_code == rls_v3.RateLimitResponse.OVER_LIMIT
        assert len(resp.statuses) == 3
        s0 = resp.statuses[0]
        assert s0.code == rls_v3.RateLimitResponse.OK
        assert s0.current_limit.requests_per_unit == 10
        assert s0.current_limit.unit == rls_v3.RateLimitResponse.RateLimit.MINUTE
        assert s0.limit_remaining == 9
        assert s0.duration_until_reset.seconds == 30
        assert not resp.statuses[2].HasField("current_limit")
        assert resp.response_headers_to_add[0].key == "x-ratelimit-throttle-ms"
        assert resp.response_headers_to_add[0].value == "250"

    def test_response_to_v2_headers_field(self):
        """v2 carries response headers in `headers`
        (ratelimit_legacy.go:94-150)."""
        resp = proto_adapter.response_to_v2(
            Code.OK, self._statuses(), [HeaderValue("h", "v")]
        )
        assert resp.overall_code == rls_v2.RateLimitResponse.OK
        assert resp.headers[0].key == "h"

    def test_v3_v2_wire_compatible(self):
        """The v2 and v3 request messages are wire-identical — the reference
        relies on this adapting legacy traffic."""
        v3 = rls_v3.RateLimitRequest(domain="d", hits_addend=1)
        v3.descriptors.add().entries.add(key="k", value="v")
        v2 = rls_v2.RateLimitRequest.FromString(v3.SerializeToString())
        assert v2.domain == "d"
        assert v2.descriptors[0].entries[0].key == "k"


class TestRuntimeLoader:
    def _mkconfig(self, root, name, text="domain: d\n"):
        config = root / "config"
        config.mkdir(parents=True, exist_ok=True)
        (config / name).write_text(text)

    def test_key_convention(self, tmp_path):
        """config/basic.yaml -> key `config.basic` (goruntime convention, so
        the service's `config.` filter works, ratelimit.go:94-102)."""
        self._mkconfig(tmp_path, "basic.yaml", "x")
        entries, _sig = scan_directory(str(tmp_path))
        assert entries == {"config.basic": "x"}

    def test_binary_file_survives_scan_and_fails_load_cleanly(self, tmp_path):
        """A stray binary file in the config dir must not raise
        UnicodeDecodeError out of the scan (that would kill the reload
        thread); it must reach the YAML loader as invalid text so the
        reload counts config_load_error and keeps the last good config."""
        from api_ratelimit_tpu.config.loader import ConfigFile, load_config
        from api_ratelimit_tpu.models.config import ConfigError
        from api_ratelimit_tpu.stats.sinks import NullSink
        from api_ratelimit_tpu.stats.store import Store

        config = tmp_path / "config"
        config.mkdir(parents=True)
        (config / "junk.yaml").write_bytes(b"\xff\xfe\x00bad: [\x9c")
        entries, _sig = scan_directory(str(tmp_path))
        assert "config.junk" in entries  # scanned, not skipped or crashed
        with pytest.raises(ConfigError):
            load_config(
                [ConfigFile(name="config.junk", contents=entries["config.junk"])],
                Store(NullSink()).scope("t"),
            )

    def test_refresh_detects_changes(self, tmp_path):
        self._mkconfig(tmp_path, "a.yaml", "one")
        loader = DirectoryRuntimeLoader(str(tmp_path))
        fired = []
        loader.add_update_callback(lambda: fired.append(1))
        assert loader.refresh() is False  # unchanged

        self._mkconfig(tmp_path, "b.yaml", "two")
        assert loader.refresh() is True
        assert fired == [1]
        snap = loader.snapshot()
        assert list(snap.keys()) == ["config.a", "config.b"]
        assert snap.get("config.b") == "two"

    def test_symlink_swap(self, tmp_path):
        """Deploys swap a `current` symlink atomically; a re-walk through the
        link must observe the new tree (RUNTIME_WATCH_ROOT deploys)."""
        v1 = tmp_path / "v1"
        v2 = tmp_path / "v2"
        self._mkconfig(v1, "r.yaml", "old")
        self._mkconfig(v2, "r.yaml", "new")
        current = tmp_path / "current"
        current.symlink_to(v1)
        loader = DirectoryRuntimeLoader(str(current))
        assert loader.snapshot().get("config.r") == "old"

        tmp = tmp_path / "current.tmp"
        tmp.symlink_to(v2)
        os.replace(tmp, current)
        assert loader.refresh() is True
        assert loader.snapshot().get("config.r") == "new"

    def test_ignore_dotfiles(self, tmp_path):
        self._mkconfig(tmp_path, "a.yaml", "x")
        self._mkconfig(tmp_path, ".hidden.yaml", "secret")
        entries, _ = scan_directory(str(tmp_path), ignore_dotfiles=True)
        assert list(entries) == ["config.a"]
        entries, _ = scan_directory(str(tmp_path), ignore_dotfiles=False)
        assert "config..hidden" in entries

    def _wait_for(self, pred, timeout=5.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return True
            time.sleep(0.02)
        return False

    def test_inotify_watcher_event_driven(self, tmp_path):
        """RUNTIME_WATCHER=inotify (VERDICT r4 weak #6): changes are seen
        without any polling — the poll interval and safety rescan are set
        far beyond the wait window, so only an inotify event can deliver
        the update."""
        if sys.platform != "linux":
            pytest.skip("inotify is Linux-only")
        self._mkconfig(tmp_path, "a.yaml", "one")
        loader = DirectoryRuntimeLoader(
            str(tmp_path),
            watcher="inotify",
            poll_interval_seconds=3600.0,
            safety_rescan_seconds=3600.0,
        )
        fired = []
        loader.add_update_callback(lambda: fired.append(1))
        try:
            loader.start_watching()
            assert loader.watching_with == "inotify"
            self._mkconfig(tmp_path, "b.yaml", "two")
            assert self._wait_for(lambda: fired), "inotify never delivered"
            assert loader.snapshot().get("config.b") == "two"
        finally:
            loader.stop()

    def test_inotify_sees_symlink_swap(self, tmp_path):
        """A deploy that atomically repoints `current` changes nothing under
        the OLD target — the parent-directory watch must catch it."""
        if sys.platform != "linux":
            pytest.skip("inotify is Linux-only")
        v1, v2 = tmp_path / "v1", tmp_path / "v2"
        self._mkconfig(v1, "r.yaml", "old")
        self._mkconfig(v2, "r.yaml", "new")
        current = tmp_path / "current"
        current.symlink_to(v1)
        loader = DirectoryRuntimeLoader(
            str(current),
            watcher="inotify",
            poll_interval_seconds=3600.0,
            safety_rescan_seconds=3600.0,
        )
        try:
            loader.start_watching()
            tmp = tmp_path / "current.tmp"
            tmp.symlink_to(v2)
            os.replace(tmp, current)
            assert self._wait_for(
                lambda: loader.snapshot().get("config.r") == "new"
            ), "symlink swap never observed"
        finally:
            loader.stop()

    def test_watcher_auto_falls_back_to_poll(self, tmp_path, monkeypatch):
        """auto mode degrades to polling when inotify cannot start."""
        from api_ratelimit_tpu.server import runtime_loader as rl

        self._mkconfig(tmp_path, "a.yaml", "one")

        def boom(paths):
            raise OSError("no inotify here")

        monkeypatch.setattr(rl, "_InotifyWatcher", boom)
        loader = rl.DirectoryRuntimeLoader(
            str(tmp_path), watcher="auto", poll_interval_seconds=0.05
        )
        try:
            loader.start_watching()
            assert loader.watching_with == "poll"
            self._mkconfig(tmp_path, "b.yaml", "two")
            assert self._wait_for(lambda: loader.snapshot().get("config.b") == "two")
        finally:
            loader.stop()

    def test_bad_watcher_mode_rejected(self, tmp_path):
        with pytest.raises(ValueError):
            DirectoryRuntimeLoader(str(tmp_path), watcher="fswatch")

    def test_inotify_rebuild_failure_falls_back_to_poll(self, tmp_path):
        """Mid-flight inotify failure (e.g. watch-limit exhaustion during a
        deploy burst) must degrade to polling, not kill hot reload."""
        if sys.platform != "linux":
            pytest.skip("inotify is Linux-only")
        self._mkconfig(tmp_path, "a.yaml", "one")
        loader = DirectoryRuntimeLoader(
            str(tmp_path),
            watcher="inotify",
            poll_interval_seconds=0.05,
            safety_rescan_seconds=3600.0,
        )
        try:
            loader.start_watching()
            assert loader.watching_with == "inotify"

            def boom():
                raise OSError("inotify watch limit reached")

            loader._inotify.rebuild = boom
            self._mkconfig(tmp_path, "b.yaml", "two")  # event -> failed rebuild
            assert self._wait_for(lambda: loader.watching_with == "poll")
            # the poll loop keeps detecting changes
            self._mkconfig(tmp_path, "c.yaml", "three")
            assert self._wait_for(
                lambda: loader.snapshot().get("config.c") == "three"
            )
        finally:
            loader.stop()


class TestConfigCheckCmd:
    def test_valid_config(self, tmp_path, capsys):
        from api_ratelimit_tpu.cmd.config_check_cmd import main

        (tmp_path / "ok.yaml").write_text(
            "domain: d\ndescriptors:\n  - key: k\n"
        )
        assert main(["-config_dir", str(tmp_path)]) == 0

    def test_invalid_config_exits_nonzero(self, tmp_path, capsys):
        from api_ratelimit_tpu.cmd.config_check_cmd import main

        (tmp_path / "bad.yaml").write_text("domain: d\nunknown_field: 1\n")
        assert main(["-config_dir", str(tmp_path)]) == 1
        assert "error loading config" in capsys.readouterr().err


class TestClientCmd:
    def test_parse_descriptor(self):
        from api_ratelimit_tpu.cmd.client_cmd import parse_descriptor

        d = parse_descriptor("database=users,tier=gold")
        assert d.entries[0].key == "database"
        assert d.entries[0].value == "users"
        assert d.entries[1].key == "tier"

        with pytest.raises(ValueError):
            parse_descriptor("noequals")


class TestInvalidOverrideUnit:
    def test_v3_invalid_unit_raises_service_error(self):
        """proto3 preserves out-of-range enum ints; a bad override unit must
        surface as a request error, not an uncaught ValueError."""
        from api_ratelimit_tpu.service.ratelimit import ServiceError

        msg = rls_v3.RateLimitRequest(domain="d")
        d = msg.descriptors.add()
        d.entries.add(key="k", value="v")
        d.limit.requests_per_unit = 5
        d.limit.unit = 7  # not a valid RateLimitUnit
        with pytest.raises(ServiceError, match="invalid limit override unit"):
            proto_adapter.request_from_v3(msg)
