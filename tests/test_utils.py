"""Unit tests for utils + models foundations."""

import pytest

from api_ratelimit_tpu.models import Unit, unit_to_divider, unit_from_string
from api_ratelimit_tpu.utils import (
    BasicSampler,
    BurstSampler,
    FakeTimeSource,
    RandomSampler,
    calculate_reset,
)


def test_unit_to_divider():
    assert unit_to_divider(Unit.SECOND) == 1
    assert unit_to_divider(Unit.MINUTE) == 60
    assert unit_to_divider(Unit.HOUR) == 3600
    assert unit_to_divider(Unit.DAY) == 86400
    with pytest.raises(ValueError):
        unit_to_divider(Unit.UNKNOWN)


def test_unit_from_string():
    assert unit_from_string("second") == Unit.SECOND
    assert unit_from_string("MINUTE") == Unit.MINUTE
    assert unit_from_string("Hour") == Unit.HOUR
    assert unit_from_string("day") == Unit.DAY
    assert unit_from_string("unknown") is None
    assert unit_from_string("fortnight") is None


def test_calculate_reset():
    # now=1234: second window resets in 1s, minute window in 60 - 34 = 26s.
    assert calculate_reset(Unit.SECOND, 1234) == 1
    assert calculate_reset(Unit.MINUTE, 1234) == 26
    assert calculate_reset(Unit.HOUR, 1234) == 3600 - 1234
    assert calculate_reset(Unit.DAY, 1234) == 86400 - 1234


def test_fake_time_source():
    ts = FakeTimeSource(100)
    assert ts.unix_now() == 100
    ts.sleep(5)
    assert ts.unix_now() == 105
    assert ts.sleeps == [5]


def test_basic_sampler():
    s = BasicSampler(3)
    results = [s.sample() for _ in range(9)]
    assert results == [True, False, False] * 3
    assert BasicSampler(1).sample() is True


def test_random_sampler_bounds():
    assert RandomSampler(0).sample() is False
    assert RandomSampler(1).sample() is True


def test_burst_sampler():
    s = BurstSampler(burst=3, period_seconds=100.0, next_sampler=None)
    assert [s.sample() for _ in range(5)] == [True, True, True, False, False]

    always = BasicSampler(1)
    s2 = BurstSampler(burst=1, period_seconds=100.0, next_sampler=always)
    assert [s2.sample() for _ in range(3)] == [True, True, True]


def test_assertx_location():
    from api_ratelimit_tpu.assertx import AssertionFailure, assert_

    assert_(True, "fine")
    with pytest.raises(AssertionFailure, match="test_utils.py"):
        assert_(False, "boom")
