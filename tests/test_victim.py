"""Tiered slab tests: the host-RAM victim tier under keyspace overload.

The acceptance ladder (ISSUE r18):

  * VictimTier unit semantics — keep-the-newest merge, value-ranked
    overflow with the lost-count ledger, TTL/window reclamation, the
    sticky watermark, export/import, and the probe-chain invariants
    under overflow churn;
  * the slab_promote_rows kernel — swap semantics, stale no-op, the
    displaced readback, same-slot serialization, inert padding;
  * the engine hierarchy end-to-end — demote readback drains to the
    tier, a reappearing key promotes and RESUMES mid-window;
  * the differential oracle bound — at 5x slab capacity the tier-on
    engine's false admits against the exact unbounded VictimOracle are
    <= slab contention drops + tier overflow_lost_count_sum, and a
    structured stream drives both terms (and so the false admits) to
    exactly ZERO, while the tier-off control pins a non-zero count;
  * the VICTIM_TIER_ENABLED=false rollback arm — byte-identical wire
    rows, verdicts, and slab bytes (spy-pinned, the test_hotkeys.py
    discipline), plus the victim=False kernel arity gate;
  * sketch-hot keys never demote — set pressure parks them in the
    unconditional re-inject queue instead of the tier;
  * the victim.demote / victim.promote chaos sites;
  * victim.snap riding the snapshot set (FLAG_VICTIM, boot reconcile).

The SIGKILL-under-eviction-pressure chaos acceptance lives in
tests/test_chaos.py (TestSigkillVictimTier).
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from api_ratelimit_tpu.backends.tpu import SlabDeviceEngine, _Item
from api_ratelimit_tpu.backends.victim import VictimTier, _OCCUPIED
from api_ratelimit_tpu.ops.slab import (
    ROW_WIDTH,
    make_slab,
    slab_promote_rows,
    slab_step_after,
)
from api_ratelimit_tpu.persist.snapshot import (
    COL_COUNT,
    COL_DIVIDER,
    COL_EXPIRE,
    COL_FP_HI,
    COL_FP_LO,
    COL_WINDOW,
)
from api_ratelimit_tpu.testing.faults import FaultInjector
from api_ratelimit_tpu.testing.oracle import VictimOracle
from api_ratelimit_tpu.utils import FakeTimeSource

NOW = 1_000_000


def row(fp_lo, fp_hi, count, window=NOW, expire=NOW + 3600, divider=3600,
        prev=0, aux=0):
    return np.array(
        [fp_lo, fp_hi, count, window, expire, divider, prev, aux],
        dtype=np.uint32,
    )


def rows(*rs):
    return np.stack(rs)


# -- fingerprint construction -------------------------------------------
#
# Engines below run n_slots=8 / ways=2 -> 4 sets; set = fp_lo & 3. uid
# rides fp_lo bits 2+ (distinct keys, same set) and fp_hi's TOP-16 bits
# (the kernel's winner-per-way rank needs distinct top bits among
# colliding distinct keys — testing/oracle.py SetSlabOracle commentary).


def fp_of(set_idx: int, uid: int) -> int:
    fp_lo = (set_idx & 3) | (uid << 2)
    fp_hi = (uid + 1) << 16
    return (fp_hi << 32) | fp_lo


def split(fp: int) -> tuple[int, int]:
    return fp & 0xFFFFFFFF, fp >> 32


def make_engine(victim_max_rows=64, ts=None, **kw):
    kw.setdefault("n_slots", 8)
    kw.setdefault("ways", 2)
    kw.setdefault("buckets", (16,))
    kw.setdefault("use_pallas", False)
    return SlabDeviceEngine(
        ts or FakeTimeSource(NOW),
        victim_max_rows=victim_max_rows,
        **kw,
    )


def item(fp, hits=1, limit=100, divider=3600):
    return _Item(fp=fp, hits=hits, limit=limit, divider=divider, jitter=0)


class TestVictimTierUnit:
    def test_insert_and_lookup_roundtrip(self):
        t = VictimTier(max_rows=8)
        assert t.insert(rows(row(5, 9, 7)), NOW) == 1
        assert t.rows == 1 and t.demotes_total == 1
        hit = t.lookup_batch(np.array([5]), np.array([9]))
        assert hit.shape == (1, ROW_WIDTH)
        assert int(hit[0, COL_COUNT]) == 7
        # lookups return copies; the row stays until retire confirms
        assert t.rows == 1
        assert t.lookup_batch(np.array([6]), np.array([9])) is None

    def test_zero_lanes_skipped(self):
        t = VictimTier(max_rows=8)
        blk = np.zeros((4, ROW_WIDTH), dtype=np.uint32)
        blk[2] = row(1, 2, 3)
        assert t.insert(blk, NOW) == 1
        assert t.rows == 1

    def test_merge_keeps_the_newest(self):
        t = VictimTier(max_rows=8)
        t.insert(rows(row(1, 2, count=5, window=NOW)), NOW)
        # older window loses; same window, lower count loses
        t.insert(rows(row(1, 2, count=50, window=NOW - 3600)), NOW)
        t.insert(rows(row(1, 2, count=3, window=NOW)), NOW)
        got = t.lookup_batch(np.array([1]), np.array([2]))
        assert int(got[0, COL_COUNT]) == 5
        # newer window wins even with a lower count
        t.insert(rows(row(1, 2, count=1, window=NOW + 3600)), NOW)
        got = t.lookup_batch(np.array([1]), np.array([2]))
        assert int(got[0, COL_COUNT]) == 1
        assert t.rows == 1 and t.merges_total == 3

    def test_retire_only_landed(self):
        t = VictimTier(max_rows=8)
        r1, r2 = row(1, 2, 3), row(5, 6, 7)
        t.insert(rows(r1, r2), NOW)
        assert t.retire(rows(r1, r2), np.array([True, False])) == 1
        assert t.rows == 1 and t.promotes_total == 1
        assert t.lookup_batch(np.array([1]), np.array([2])) is None
        assert t.lookup_batch(np.array([5]), np.array([6])) is not None

    def test_reclaim_drops_dead_and_window_ended(self):
        t = VictimTier(max_rows=8)
        t.insert(
            rows(
                row(1, 2, 3, expire=NOW + 10),  # live, window current
                row(5, 6, 7, expire=NOW - 1),  # TTL-dead
                # fixed window ended (window + div <= now) but TTL alive
                row(9, 10, 11, window=NOW - 7200, expire=NOW + 10),
            ),
            NOW,
        )
        assert t.rows == 3
        dropped = t.reclaim(NOW)
        assert dropped == 2 and t.rows == 1 and t.reclaimed_total == 2
        assert t.lookup_batch(np.array([1]), np.array([2])) is not None

    def test_overflow_is_value_ranked_and_ledgered(self):
        t = VictimTier(max_rows=2)
        t.insert(rows(row(1, 2, count=10), row(5, 6, count=20)), NOW)
        # lower than the table minimum: the INCOMING row drops
        assert t.insert(rows(row(9, 10, count=4)), NOW) == 0
        assert t.rows == 2
        assert t.overflow_drops_total == 1
        assert t.overflow_lost_count_sum == 4
        # higher than the minimum: the table's argmin-count row drops
        assert t.insert(rows(row(13, 14, count=30)), NOW) == 1
        assert t.rows == 2
        assert t.overflow_drops_total == 2
        assert t.overflow_lost_count_sum == 4 + 10
        assert t.lookup_batch(np.array([1]), np.array([2])) is None
        assert t.lookup_batch(np.array([13]), np.array([14])) is not None

    def test_overflow_reclaims_first(self):
        t = VictimTier(max_rows=2)
        t.insert(rows(row(1, 2, 3, expire=NOW - 1), row(5, 6, 7)), NOW)
        # the dead row reclaims, so this insert costs no overflow drop
        assert t.insert(rows(row(9, 10, count=1)), NOW) == 1
        assert t.overflow_drops_total == 0 and t.reclaimed_total == 1
        assert t.rows == 2

    def test_watermark_sticky_until_occupancy_falls(self):
        t = VictimTier(max_rows=4, watermark=0.5)
        assert t.watermark_reason() is None
        t.insert(rows(row(1, 2, 3), row(5, 6, 7)), NOW)
        assert t.watermark_reason() is not None
        # stays raised while occupancy holds
        assert "victim tier pressure" in t.watermark_reason()
        t.retire(rows(row(1, 2, 3)), np.array([True]))
        assert t.watermark_reason() is None

    def test_export_import_roundtrip(self):
        t = VictimTier(max_rows=8)
        t.insert(rows(row(1, 2, 3), row(5, 6, 7)), NOW)
        exported = t.export_rows()
        assert exported.shape == (2, ROW_WIDTH)
        t2 = VictimTier(max_rows=8)
        assert t2.import_rows(exported, NOW) == 2
        got = t2.lookup_batch(np.array([1, 5]), np.array([2, 6]))
        assert got.shape == (2, ROW_WIDTH)

    def test_import_reapplies_bounds(self):
        big = VictimTier(max_rows=16)
        blk = np.stack([row(i * 4 + 1, i + 1, count=i + 1) for i in range(8)])
        big.insert(blk, NOW)
        small = VictimTier(max_rows=2)
        small.import_rows(big.export_rows(), NOW)
        assert small.rows <= 2  # never overflows the running config

    def test_describe_document(self):
        t = VictimTier(max_rows=8)
        t.insert(rows(row(1, 2, 3, window=NOW - 30)), NOW)
        doc = t.describe(NOW)
        assert doc["rows"] == 1 and doc["max_rows"] == 8
        assert doc["age_histogram"]["<60s"] == 1
        assert sum(doc["age_histogram"].values()) == 1
        assert doc["overflow_lost_count_sum"] == 0

    def test_overflow_churn_keeps_invariants(self):
        # the regression stress: overflow/rehash must never leave a
        # stale free-slot — every surviving row stays findable and the
        # bound holds through heavy churn
        t = VictimTier(max_rows=32)
        rng = np.random.default_rng(11)
        for step in range(400):
            uid = int(rng.integers(1, 200))
            t.insert(
                rows(row(uid * 4 + 1, uid, count=int(rng.integers(1, 50)))),
                NOW,
            )
            assert t.rows <= 32
        occ = t._slot_state == _OCCUPIED
        assert int(occ.sum()) == t.rows
        for r in t._table[occ]:
            got = t.lookup_batch(
                np.array([int(r[COL_FP_LO])]), np.array([int(r[COL_FP_HI])])
            )
            assert got is not None and int(got[0, COL_COUNT]) == int(
                r[COL_COUNT]
            )


def _promote(state, blk, now=NOW, ways=2):
    state, landed, displaced = slab_promote_rows(
        state, jnp.asarray(blk, dtype=jnp.uint32), now, ways=ways
    )
    return state, np.asarray(landed), np.asarray(displaced)


class TestPromoteKernel:
    def _occupied_set(self, state, set_idx, uids, counts, ways=2):
        """Fill a set's ways via real steps so the table rows carry the
        kernel's own wire format."""
        table = np.array(state.table)
        for uid, count in zip(uids, counts):
            lo, hi = split(fp_of(set_idx, uid))
            free = None
            base = set_idx * ways
            for w in range(ways):
                if table[base + w, COL_EXPIRE] == 0:
                    free = base + w
                    break
            table[free] = row(lo, hi, count)
        from api_ratelimit_tpu.ops.slab import SlabState

        return SlabState(table=jnp.asarray(table))

    def test_promote_lands_in_empty_way(self):
        state = make_slab(8)
        lo, hi = split(fp_of(1, 3))
        state, landed, _ = _promote(state, rows(row(lo, hi, count=9)))
        assert landed.tolist() == [True]
        table = np.asarray(state.table)
        hit = (table[:, COL_FP_LO] == lo) & (table[:, COL_FP_HI] == hi)
        assert int(table[hit][0, COL_COUNT]) == 9

    def test_promote_swaps_and_reports_displaced(self):
        state = make_slab(8)
        state = self._occupied_set(state, 2, uids=(1, 2), counts=(5, 3))
        lo, hi = split(fp_of(2, 7))
        state, landed, displaced = _promote(state, rows(row(lo, hi, 40)))
        assert landed.tolist() == [True]
        live = displaced[displaced[:, COL_EXPIRE] != 0]
        # the scan's victim way (lowest count live: count 3) came back
        assert live.shape[0] == 1
        assert int(live[0, COL_COUNT]) == 3
        table = np.asarray(state.table)
        assert int(table[(table[:, COL_FP_LO] == lo)][0, COL_COUNT]) == 40

    def test_stale_promote_is_noop_but_lands(self):
        # the slab re-created the row with a NEWER window while the copy
        # sat demoted: keep-the-newest — the tier copy is provably stale,
        # reported landed so the tier retires it
        state = make_slab(8)
        lo, hi = split(fp_of(0, 4))
        state = self._occupied_set(state, 0, uids=(4,), counts=(8,))
        stale = row(lo, hi, count=99, window=NOW - 3600)
        state, landed, displaced = _promote(state, rows(stale))
        assert landed.tolist() == [True]
        table = np.asarray(state.table)
        assert int(table[(table[:, COL_FP_LO] == lo)][0, COL_COUNT]) == 8
        assert displaced[displaced[:, COL_EXPIRE] != 0].shape[0] == 0

    def test_newer_promote_overwrites_match(self):
        state = make_slab(8)
        lo, hi = split(fp_of(0, 4))
        state = self._occupied_set(state, 0, uids=(4,), counts=(8,))
        newer = row(lo, hi, count=12, window=NOW)  # same window, more count
        state, landed, _ = _promote(state, rows(newer))
        assert landed.tolist() == [True]
        table = np.asarray(state.table)
        assert int(table[(table[:, COL_FP_LO] == lo)][0, COL_COUNT]) == 12

    def test_same_slot_collision_serializes(self):
        # two promoted rows whose scan picks the same way: the last write
        # wins, the loser stays un-landed (retries from the tier later)
        state = make_slab(8)
        state = self._occupied_set(state, 3, uids=(1, 2), counts=(50, 60))
        lo_a, hi_a = split(fp_of(3, 7))
        lo_b, hi_b = split(fp_of(3, 8))
        blk = rows(row(lo_a, hi_a, 5), row(lo_b, hi_b, 6))
        state, landed, _ = _promote(state, blk)
        assert sorted(landed.tolist()) == [False, True]
        table = np.asarray(state.table)
        present = {
            (int(r[COL_FP_LO]), int(r[COL_FP_HI]))
            for r in table
            if r[COL_EXPIRE]
        }
        winners = {(lo_a, hi_a), (lo_b, hi_b)} & present
        assert len(winners) == 1

    def test_padding_rows_inert(self):
        state = make_slab(8)
        blk = np.zeros((4, ROW_WIDTH), dtype=np.uint32)
        lo, hi = split(fp_of(1, 2))
        blk[1] = row(lo, hi, 3)
        state, landed, displaced = _promote(state, blk)
        assert landed.tolist() == [False, True, False, False]
        table = np.asarray(state.table)
        assert int((table[:, COL_EXPIRE] != 0).sum()) == 1
        assert displaced[displaced[:, COL_EXPIRE] != 0].shape[0] == 0

    def test_expired_tier_row_drops_unlanded(self):
        state = make_slab(8)
        lo, hi = split(fp_of(1, 2))
        dead = row(lo, hi, 3, expire=NOW - 5)
        state, landed, _ = _promote(state, rows(dead))
        assert landed.tolist() == [False]
        assert int((np.asarray(state.table)[:, COL_EXPIRE] != 0).sum()) == 0


class TestEngineHierarchy:
    def test_demote_then_promote_resumes_mid_window(self):
        eng = make_engine()
        fa, fb, fc = fp_of(0, 1), fp_of(0, 2), fp_of(0, 3)
        for _ in range(5):
            eng._launch([item(fa)])
        for _ in range(3):
            eng._launch([item(fb)])
        # set 0 is full (A count 5, B count 3); C's insert demotes B
        eng._launch([item(fc)])
        tier = eng.victim_tier
        assert tier.rows == 1 and tier.demotes_total == 1
        lo_b, hi_b = split(fb)
        got = tier.lookup_batch(np.array([lo_b]), np.array([hi_b]))
        assert int(got[0, COL_COUNT]) == 3
        # B reappears: the promote rides ahead of the step, so THIS
        # launch already sees the restored counter -> 4, not 1
        after = eng._launch([item(fb)])
        assert after == [4]
        assert tier.promotes_total == 1
        # the promote displaced a live row, which re-demoted
        assert tier.demotes_total == 2 and tier.rows == 1

    def test_victim_debug_document(self):
        eng = make_engine()
        doc = eng.victim_debug()
        assert doc["enabled"] is True
        assert doc["rows"] == 0 and doc["pending_hot"] == 0
        off = make_engine(victim_max_rows=0)
        assert off.victim_debug() == {"enabled": False}
        assert off.victim_tier is None and not off.victim_enabled

    def test_watermark_probe_via_engine(self):
        eng = make_engine(victim_max_rows=2, victim_watermark=0.5)
        assert eng.victim_watermark_reason() is None
        eng.victim_tier.insert(rows(row(1, 2, 3)), NOW)
        assert "victim tier pressure" in eng.victim_watermark_reason()
        off = make_engine(victim_max_rows=0)
        assert off.victim_watermark_reason() is None


class TestDifferentialOracle:
    """The tentpole acceptance: at 5x slab capacity (40 keys over an
    8-row slab) the tier-on engine admits EXACTLY what the unbounded
    per-key oracle admits — the bound false_admits <= slab contention
    drops + tier overflow_lost_count_sum, with a structured stream (one
    key per set per batch, keyspace within VICTIM_MAX_ROWS, fixed
    clock) driving both loss terms to zero. The tier-off control under
    the identical stream pins a NON-zero false-admit count."""

    LIMIT = 3
    ROUNDS = 60
    KEYS_PER_SET = 10  # 4 sets x 10 = 40 keys = 5x the 8-row slab

    def _stream(self):
        for r in range(self.ROUNDS):
            yield [
                fp_of(s, 1 + s * self.KEYS_PER_SET + (r % self.KEYS_PER_SET))
                for s in range(4)
            ]

    def _drive(self, eng):
        oracle = VictimOracle()
        false_admits = false_overs = oracle_overs = 0
        for batch in self._stream():
            afters = eng._launch(
                [item(fp, limit=self.LIMIT) for fp in batch]
            )
            codes = oracle.step_batch(
                [(*split(fp), 1, self.LIMIT, 3600, 0) for fp in batch], NOW
            )
            for after, code in zip(afters, codes):
                engine_over = after > self.LIMIT
                oracle_overs += code == 2
                if code == 2 and not engine_over:
                    false_admits += 1
                if code == 1 and engine_over:
                    false_overs += 1
        return false_admits, false_overs, oracle_overs

    def test_tier_on_false_admits_zero_at_5x_capacity(self):
        eng = make_engine(victim_max_rows=64)
        false_admits, false_overs, oracle_overs = self._drive(eng)
        # the stream crosses the limit hard: half of all decisions are
        # OVER in the exact model — the comparison has teeth
        assert oracle_overs == 4 * self.KEYS_PER_SET * (
            self.ROUNDS // self.KEYS_PER_SET - self.LIMIT
        )
        # the stated bound's loss terms, each provably zero here:
        drops = eng.health_snapshot()["drops"]
        lost = eng.victim_tier.overflow_lost_count_sum
        assert drops == 0, "one key per set per batch: no contention"
        assert lost == 0, "40 keys vs max_rows=64: no tier overflow"
        assert false_admits <= drops + lost  # the bound itself
        assert false_admits == 0, (
            f"victim tier must end silent live-counter loss "
            f"(false admits: {false_admits})"
        )
        # and the hierarchy never overcounts either direction
        assert false_overs == 0
        # the tier actually worked for a living: every round past the
        # first sweep promotes 4 rows and demotes their displacements
        tier = eng.victim_tier
        assert tier.promotes_total > 100
        assert tier.demotes_total > 100
        assert tier.rows == 40 - 8  # everything not on the slab is here

    def test_tier_off_control_pins_nonzero_loss(self):
        eng = make_engine(victim_max_rows=0)
        false_admits, _false_overs, oracle_overs = self._drive(eng)
        assert oracle_overs > 0
        # without the tier every live eviction resets a counter: the
        # engine re-admits keys the exact model already cut off
        assert false_admits > 0, (
            "the control arm must exhibit the loss the tier ends — if "
            "this is 0 the differential test lost its teeth"
        )
        assert eng.health_snapshot()["evictions_live"] > 0


class TestRollbackArm:
    """VICTIM_TIER_ENABLED=false must be the pre-tier engine byte for
    byte: identical wire rows, identical verdicts, identical slab bytes
    (the spy pin, test_hotkeys.py discipline), and a launch tuple with
    NO victim readback (the kernel arity gate)."""

    def _make_service(self, victim_max_rows):
        from test_algorithms import FakeRuntime

        from api_ratelimit_tpu.limiter import BaseRateLimiter
        from api_ratelimit_tpu.backends.tpu import TpuRateLimitCache
        from api_ratelimit_tpu.service.ratelimit import RateLimitService
        from api_ratelimit_tpu.stats import Store, TestSink
        from api_ratelimit_tpu.models import Descriptor, RateLimitRequest

        yaml_text = (
            "domain: vic\n"
            "descriptors:\n"
            "  - key: k\n"
            "    rate_limit: {unit: hour, requests_per_unit: 5}\n"
        )
        ts = FakeTimeSource(NOW)
        base = BaseRateLimiter(ts, near_limit_ratio=0.8)
        cache = TpuRateLimitCache(
            base,
            n_slots=1 << 12,
            buckets=(128,),
            max_batch=128,
            use_pallas=False,
            victim_max_rows=victim_max_rows,
        )
        svc = RateLimitService(
            runtime=FakeRuntime({"config.vic": yaml_text}),
            cache=cache,
            stats_scope=Store(TestSink()).scope("ratelimit.service"),
            time_source=ts,
        )

        def req(tenant):
            return RateLimitRequest(
                domain="vic",
                descriptors=(Descriptor.of(("k", tenant)),),
                hits_addend=1,
            )

        return svc, cache, req

    def test_off_and_on_arms_agree_byte_for_byte(self):
        svc_off, cache_off, req = self._make_service(0)
        svc_on, cache_on, _ = self._make_service(1 << 10)
        assert not cache_off.engine.victim_enabled
        assert cache_on.engine.victim_enabled

        captured: dict[str, list] = {"off": [], "on": []}
        for label, cache in (("off", cache_off), ("on", cache_on)):
            real = cache._batcher._execute
            bucket = captured[label]

            def spy(blocks, _real=real, _bucket=bucket):
                _bucket.append([np.array(b) for b in blocks])
                return _real(blocks)

            cache._batcher._execute = spy

        verdicts = {"off": [], "on": []}
        for label, svc in (("off", svc_off), ("on", svc_on)):
            for i in range(8):  # crosses limit 5: OK and OVER both pinned
                code, _, _ = svc.should_rate_limit(req("t"))
                verdicts[label].append(code)
            for i in range(4):
                code, _, _ = svc.should_rate_limit(req(f"cold{i}"))
                verdicts[label].append(code)

        # identical verdict stream
        assert verdicts["off"] == verdicts["on"]
        # identical wire rows: the tier must not perturb the submit path
        rows_off = np.concatenate(
            [b for bs in captured["off"] for b in bs], axis=1
        )
        rows_on = np.concatenate(
            [b for bs in captured["on"] for b in bs], axis=1
        )
        np.testing.assert_array_equal(rows_off, rows_on)
        # identical slab bytes: with no eviction pressure the tier is
        # pure SIBLING state — the slab never hears about it
        np.testing.assert_array_equal(
            np.asarray(cache_off.engine._state.table),
            np.asarray(cache_on.engine._state.table),
        )
        assert cache_on.engine.victim_tier.rows == 0
        assert cache_off.victim_debug() == {"enabled": False}

    def test_victim_false_compiles_pre_tier_arity(self):
        # the wire/program half of the byte-identity gate: victim=False
        # (and the DEFAULT — no caller opts in accidentally) returns the
        # pre-tier 3-tuple; victim=True appends exactly one trailing
        # uint32[b, ROW_WIDTH] readback
        import inspect

        sig = inspect.signature(slab_step_after)
        assert sig.parameters["victim"].default is False

        packed = np.zeros((7, 16), dtype=np.uint32)
        lo, hi = split(fp_of(0, 1))
        packed[0, 0], packed[1, 0] = lo, hi
        packed[2, 0], packed[3, 0] = 1, 10
        packed[4, 0] = 3600
        packed[6, 0] = NOW
        out_default = slab_step_after(
            make_slab(8), jnp.asarray(packed), ways=2, use_pallas=False
        )
        assert len(out_default) == 3
        out_on = slab_step_after(
            make_slab(8),
            jnp.asarray(packed),
            ways=2,
            use_pallas=False,
            victim=True,
        )
        assert len(out_on) == 4
        assert out_on[-1].shape == (16, ROW_WIDTH)
        assert out_on[-1].dtype == jnp.uint32


class TestHotKeysNeverDemote:
    def test_sketch_hot_key_refuses_demotion_under_set_pressure(self):
        eng = make_engine()
        hot = fp_of(0, 1)
        lo_h, hi_h = split(hot)
        # drive the hot key to a LOW count so the eviction scan would
        # pick it, then pin it hot (PR 15's top-K feeds hot_fps in
        # production; the test pins the set directly)
        eng._launch([item(hot)])
        eng._hot_fps = frozenset({hot})
        # sustained set pressure: higher-count keys pile into set 0
        for uid in range(2, 8):
            for _ in range(3):
                eng._launch([item(fp_of(0, uid))])
            # the hot fp must NEVER appear in the tier
            exported = eng.victim_tier.export_rows()
            present = {
                (int(r[COL_FP_LO]), int(r[COL_FP_HI])) for r in exported
            }
            assert (lo_h, hi_h) not in present
        assert eng._victim_hot_refusals > 0
        # the parked row re-injects unconditionally: the next launch for
        # ANY key finds the hot row back on the slab, counter intact
        after = eng._launch([item(hot)])
        assert after == [2]  # resumed at 1, not reset to 0
        with eng._victim_lock:
            assert (lo_h, hi_h) not in eng._promote_pending


class TestFaultSites:
    def _pressure(self, eng):
        """One demotion's worth of set pressure (set 0 full, then one
        more key)."""
        for uid in (1, 2):
            for _ in range(3):
                eng._launch([item(fp_of(0, uid))])
        eng._launch([item(fp_of(0, 3))])

    def test_demote_drop_silently_loses_rows(self):
        inj = FaultInjector.from_spec("victim.demote:drop:1.0")
        eng = make_engine(fault_injector=inj)
        self._pressure(eng)
        assert eng.victim_tier.rows == 0
        assert eng._victim_demote_errors == 0
        assert inj.fired().get("victim.demote:drop", 0) >= 1

    def test_demote_error_counts_and_fails_open(self):
        inj = FaultInjector.from_spec("victim.demote:error:1.0")
        eng = make_engine(fault_injector=inj)
        self._pressure(eng)
        assert eng.victim_tier.rows == 0
        assert eng._victim_demote_errors >= 1
        assert eng.victim_debug()["demote_errors"] >= 1
        # serving untouched: the next launch still answers
        assert eng._launch([item(fp_of(1, 9))]) == [1]

    def test_promote_drop_leaves_rows_in_tier(self):
        eng = make_engine()
        self._pressure(eng)
        assert eng.victim_tier.rows == 1
        demoted_fp = None
        r = eng.victim_tier.export_rows()[0]
        demoted_fp = (int(r[COL_FP_HI]) << 32) | int(r[COL_FP_LO])
        inj = FaultInjector.from_spec("victim.promote:drop:1.0")
        eng._fault = inj
        # the key reappears but the promote site is down: the counter
        # does NOT resume (fresh row) — and the tier row SURVIVES
        after = eng._launch([item(demoted_fp)])
        assert after[0] == 1
        assert eng.victim_tier.rows >= 1
        assert eng._victim_promote_skips >= 1
        # the site heals: promotion is retry-forever, the counter comes
        # back keep-the-newest (the slab's fresh row is same-window with
        # a LOWER count, so the tier's copy wins)
        eng._fault = None
        after = eng._launch([item(demoted_fp)])
        assert after[0] == 4  # tier count 3 + this hit
        # the promoted fp retired from the tier (the faulted launch's
        # insert displaced ANOTHER row, which rightly stays demoted)
        exported = eng.victim_tier.export_rows()
        present = {
            (int(r[COL_FP_LO]), int(r[COL_FP_HI])) for r in exported
        }
        assert split(demoted_fp) not in present


class TestPersistRoundTrip:
    def _snap(self, eng, tmp_path):
        from api_ratelimit_tpu.persist.snapshotter import SlabSnapshotter

        return SlabSnapshotter(
            eng,
            str(tmp_path),
            interval_ms=3_600_000.0,
            time_source=eng._time_source,
        )

    def _demote_one(self, eng):
        for uid in (1, 2):
            for _ in range(3):
                eng._launch([item(fp_of(0, uid))])
        eng._launch([item(fp_of(0, 3))])

    def test_victim_snap_rides_the_snapshot_set(self, tmp_path):
        import os

        from api_ratelimit_tpu.persist.snapshotter import (
            victim_snapshot_path,
        )

        eng = make_engine()
        self._demote_one(eng)
        snap = self._snap(eng, tmp_path)
        assert snap.snapshot_once() > 0
        path = victim_snapshot_path(str(tmp_path))
        assert os.path.exists(path)

        # a fresh tier-on engine restores the demoted row and RESUMES
        eng2 = make_engine(ts=FakeTimeSource(NOW))
        snap2 = self._snap(eng2, tmp_path)
        stats = snap2.restore()
        assert stats["restored"]
        assert stats["restored_victim_rows"] == 1
        assert stats["dropped_victim_rows"] == 0
        assert eng2.victim_tier.rows == 1
        after = eng2._launch([item(fp_of(0, 2))])
        assert after == [4]  # demoted at 3, resumed mid-window

    def test_tierless_engine_ignores_victim_section(self, tmp_path):
        eng = make_engine()
        self._demote_one(eng)
        self._snap(eng, tmp_path).snapshot_once()
        off = make_engine(victim_max_rows=0, ts=FakeTimeSource(NOW))
        stats = self._snap(off, tmp_path).restore()
        assert stats["restored"]
        assert stats.get("restored_victim_rows", 0) == 0

    def test_restore_reconciles_against_the_clock(self, tmp_path):
        eng = make_engine()
        self._demote_one(eng)
        self._snap(eng, tmp_path).snapshot_once()
        # boot far past every TTL: the row reconciles away, not resumes
        late = make_engine(ts=FakeTimeSource(NOW + 86_400))
        stats = self._snap(late, tmp_path).restore()
        assert stats["restored_victim_rows"] == 0
        assert stats["dropped_victim_rows"] == 1
        assert late.victim_tier.rows == 0

    def test_corrupt_victim_file_degrades_to_tierless_restore(
        self, tmp_path
    ):
        from api_ratelimit_tpu.persist.snapshotter import (
            victim_snapshot_path,
        )

        eng = make_engine()
        self._demote_one(eng)
        self._snap(eng, tmp_path).snapshot_once()
        path = victim_snapshot_path(str(tmp_path))
        with open(path, "r+b") as f:
            f.seek(40)
            f.write(b"\xff\xff\xff\xff")
        eng2 = make_engine(ts=FakeTimeSource(NOW))
        stats = self._snap(eng2, tmp_path).restore()
        # the SLAB still restores; only the victim section is rejected
        assert stats["restored"]
        assert stats.get("restored_victim_rows", 0) == 0
        assert eng2.victim_tier.rows == 0


class TestVictimStats:
    def test_stats_flush_exports_the_envelope_and_reclaims(self):
        from api_ratelimit_tpu.backends.tpu import VictimStats
        from api_ratelimit_tpu.stats import Store, TestSink

        sink = TestSink()
        store = Store(sink)
        eng = make_engine()
        eng.victim_tier.insert(
            rows(row(1, 2, 3), row(5, 6, 7, expire=NOW - 1)), NOW
        )
        gen = VictimStats(eng, store.scope("ratelimit").scope("victim"))
        gen.generate_stats()
        store.flush()
        got = {
            name: v for name, v in sink.gauges.items() if ".victim." in name
        }
        assert got["ratelimit.victim.rows"] == 1  # the dead row reclaimed
        assert got["ratelimit.victim.demotes"] == 2
        assert got["ratelimit.victim.reclaimed"] == 1
        assert got["ratelimit.victim.watermark"] == 0
        assert got["ratelimit.victim.overflow_lost_count_sum"] == 0
