"""Restart-under-load chaos: the warm-restart acceptance tests.

Three escalating scenarios against the durability contract:

  * in-process crash simulation — traffic through the engine with periodic
    snapshots, the process "dies" (engine abandoned, NO final snapshot), a
    fresh engine restores: per-key overshoot vs the exact fixed-window
    oracle (testing/oracle.py) is bounded by one snapshot interval of
    traffic, and every disagreement fails OPEN (false_over == 0);
  * graceful drain — the final drain snapshot makes the handoff lossless:
    overshoot exactly 0;
  * a REAL kill -9 — a subprocess owns the device, snapshots every K
    batches, gets SIGKILLed mid-window; the restarted process restores and
    its counters land within one snapshot interval of the true traffic.

Plus the Runner-level wiring: SLAB_SNAPSHOT_DIR set => boot restores
before serving and stop() writes the final drain snapshot.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from api_ratelimit_tpu.backends.tpu import SlabDeviceEngine, _Item
from api_ratelimit_tpu.persist.snapshotter import SlabSnapshotter
from api_ratelimit_tpu.testing.oracle import parity_report
from api_ratelimit_tpu.utils import FakeTimeSource

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NOW = 1_700_000_000
N_KEYS = 16
LIMIT = 23
SNAP_EVERY = 5  # batches per "snapshot interval" in the simulated runs


def _engine(ts):
    return SlabDeviceEngine(
        ts, n_slots=1 << 12, use_pallas=False, buckets=(128,)
    )


def _batch(engine):
    """One round: every key once, in key order. Returns the per-key
    post-increment counters."""
    return engine.submit(
        [
            _Item(fp=5000 + k, hits=1, limit=LIMIT, divider=100_000, jitter=0)
            for k in range(N_KEYS)
        ]
    )


def _codes(afters):
    """Engine decision per item: 2 = OVER_LIMIT (after > limit), 1 = OK —
    the same rule decide() applies on device."""
    return [2 if after > LIMIT else 1 for after in afters]


def _run_phase(engine, n_batches, ids, codes, snapshotter=None):
    for i in range(n_batches):
        afters = _batch(engine)
        ids.extend(range(N_KEYS))
        codes.extend(_codes(afters))
        if snapshotter is not None and (i + 1) % SNAP_EVERY == 0:
            snapshotter.snapshot_once()


class TestCrashRestoreOracle:
    def test_crash_overshoot_bounded_by_snapshot_interval(self, tmp_path):
        """23 batches with a snapshot after every 5th (last at 20), crash
        (no drain — batches 21..23 are forgotten), restore, 8 more batches
        crossing the limit: vs the oracle the engine fails open for exactly
        the 3 lost hits per key — bounded by one snapshot interval
        (SNAP_EVERY) — and must NEVER fail closed."""
        ts = FakeTimeSource(NOW)
        ids: list[int] = []
        codes: list[int] = []

        eng = _engine(ts)
        snap = SlabSnapshotter(eng, str(tmp_path), interval_ms=60_000,
                               time_source=ts)
        _run_phase(eng, 23, ids, codes, snapshotter=snap)
        del eng  # kill -9 analog: no drain, no final snapshot

        eng2 = _engine(ts)
        snap2 = SlabSnapshotter(eng2, str(tmp_path), interval_ms=60_000,
                                time_source=ts)
        assert snap2.restore()["restored"] == N_KEYS
        _run_phase(eng2, 8, ids, codes)  # restored counters resume at 20

        report = parity_report(
            np.asarray(ids, dtype=np.int64), np.asarray(codes), LIMIT
        )
        # fail-open only: the engine must never say OVER where truth is OK
        assert report["false_over"] == 0
        # the crash lost batches 21..23 => at most one snapshot interval of
        # extra fail-open OKs per key (here exactly the 3 lost hits)
        assert 0 < report["false_ok"] <= SNAP_EVERY * N_KEYS
        # and the restored counters really continued (not a cold boot,
        # which would fail open for LIMIT extra hits per key)
        assert _batch(eng2)[0] == 20 + 8 + 1

    def test_graceful_drain_is_lossless(self, tmp_path):
        """Planned restart: drain writes the final snapshot AFTER the last
        admitted batch, so the next process agrees with the oracle
        everywhere — overshoot exactly 0."""
        ts = FakeTimeSource(NOW)
        ids: list[int] = []
        codes: list[int] = []

        eng = _engine(ts)
        snap = SlabSnapshotter(eng, str(tmp_path), interval_ms=60_000,
                               time_source=ts)
        _run_phase(eng, 28, ids, codes, snapshotter=snap)  # 28th unsnapped
        snap.drain()  # quiesce + final snapshot at batch 28

        eng2 = _engine(ts)
        snap2 = SlabSnapshotter(eng2, str(tmp_path), interval_ms=60_000,
                                time_source=ts)
        assert snap2.restore()["restored"] == N_KEYS
        _run_phase(eng2, 5, ids, codes)

        report = parity_report(
            np.asarray(ids, dtype=np.int64), np.asarray(codes), LIMIT
        )
        assert report["false_over"] == 0
        assert report["false_ok"] == 0  # ~0 loss for a planned restart
        assert report["agreement"] == 1.0


class TestRunnerWarmRestart:
    """SLAB_SNAPSHOT_DIR wired through the composition root: restore
    before serving, final snapshot on stop, staleness probe registered."""

    BASIC = """\
domain: warm
descriptors:
  - key: api
    rate_limit: {unit: hour, requests_per_unit: 10}
"""

    def _settings(self, tmp_path, snap_dir):
        from api_ratelimit_tpu.settings import Settings

        config_dir = tmp_path / "current" / "ratelimit" / "config"
        if not config_dir.exists():
            config_dir.mkdir(parents=True)
            (config_dir / "warm.yaml").write_text(self.BASIC)
        return Settings(
            port=0,
            grpc_port=0,
            debug_port=0,
            use_statsd=False,
            runtime_path=str(tmp_path / "current"),
            runtime_subdirectory="ratelimit",
            backend_type="tpu",
            tpu_slab_slots=1 << 10,
            tpu_use_pallas=False,
            expiration_jitter_max_seconds=0,
            local_cache_size_in_bytes=0,
            slab_snapshot_dir=str(snap_dir),
            slab_snapshot_interval_ms=60_000.0,
            log_level="ERROR",
        )

    def _request(self, hits):
        from api_ratelimit_tpu.models.descriptors import (
            Descriptor,
            RateLimitRequest,
        )

        return RateLimitRequest(
            domain="warm",
            descriptors=(Descriptor.of(("api", "user1")),),
            hits_addend=hits,
        )

    def test_stop_snapshots_and_next_boot_restores(self, tmp_path):
        from api_ratelimit_tpu.models.response import Code
        from api_ratelimit_tpu.runner import Runner
        from api_ratelimit_tpu.stats.sinks import TestSink

        snap_dir = tmp_path / "snapshots"
        runner = Runner(self._settings(tmp_path, snap_dir), sink=TestSink())
        runner.run_background()
        assert runner.wait_ready(10.0)
        assert runner.snapshotter is not None
        # the staleness probe is on the health surface (degraded-only)
        assert runner.server.health.degraded_reasons() == []
        code, _statuses, _headers = runner.service.should_rate_limit(
            self._request(hits=10)
        )
        assert code == Code.OK  # 10/10 used
        runner.stop()  # drain handoff: writes the final snapshot
        assert (snap_dir / "slab.snap").exists()

        runner2 = Runner(self._settings(tmp_path, snap_dir), sink=TestSink())
        runner2.run_background()
        assert runner2.wait_ready(10.0)
        try:
            assert runner2.snapshotter.restore_stats["restored"] == 1
            # the restored counter carries the 10 used hits: one more is OVER
            code, _statuses, _headers = runner2.service.should_rate_limit(
                self._request(hits=1)
            )
            assert code == Code.OVER_LIMIT
        finally:
            runner2.stop()

    def test_snapshot_disabled_by_default(self, tmp_path):
        from api_ratelimit_tpu.runner import Runner
        from api_ratelimit_tpu.settings import Settings
        from api_ratelimit_tpu.stats.sinks import TestSink

        settings = self._settings(tmp_path, tmp_path / "unused")
        settings.slab_snapshot_dir = ""
        runner = Runner(settings, sink=TestSink())
        runner.run_background()
        assert runner.wait_ready(10.0)
        try:
            assert runner.snapshotter is None
        finally:
            runner.stop()
        assert not (tmp_path / "unused").exists()


_CHILD = """\
import json, os, sys, time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)
sys.path.insert(0, {repo!r})

from api_ratelimit_tpu.backends.tpu import SlabDeviceEngine, _Item
from api_ratelimit_tpu.persist.snapshotter import SlabSnapshotter
from api_ratelimit_tpu.utils.timeutil import RealTimeSource

snap_dir, progress_path, phase = sys.argv[1], sys.argv[2], sys.argv[3]
engine = SlabDeviceEngine(
    RealTimeSource(), n_slots=1 << 12, use_pallas=False, buckets=(128,)
)
snap = SlabSnapshotter(engine, snap_dir, interval_ms=3_600_000.0)
restored = snap.restore()
KEYS = [9000 + k for k in range(8)]


def batch():
    return engine.submit(
        [
            _Item(fp=k, hits=1, limit=1_000_000, divider=1_000_000, jitter=0)
            for k in KEYS
        ]
    )


if phase == "crash":
    with open(progress_path, "a") as f:
        for i in range(100_000):  # runs until SIGKILLed
            afters = batch()
            f.write(json.dumps([i, afters[0]]) + "\\n")
            f.flush()
            os.fsync(f.fileno())
            if (i + 1) % 5 == 0:
                snap.snapshot_once()
            time.sleep(0.01)
else:
    final = None
    for _ in range(20):
        final = batch()
    print(json.dumps({{"restored": restored, "final": final}}))
"""


class TestSigkillRestart:
    def test_kill9_midwindow_restores_with_bounded_loss(self, tmp_path):
        """The real thing: the device-owner process is SIGKILLed mid-window
        (no drain, no atexit — nothing runs), a new process restores from
        the last periodic snapshot (every 5 batches) and keeps counting.
        The restored counters must land within one snapshot interval of
        the true traffic: warm (not cold), never overcounting."""
        child_py = tmp_path / "child.py"
        child_py.write_text(_CHILD.format(repo=REPO))
        snap_dir = str(tmp_path / "snaps")
        progress = tmp_path / "progress.jsonl"
        progress.touch()

        proc = subprocess.Popen(
            [sys.executable, str(child_py), snap_dir, str(progress), "crash"],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        try:
            # wait until the child has demonstrably snapshotted at least
            # twice (>= 12 batches), then kill -9 mid-stride
            deadline = time.monotonic() + 120.0
            batches_seen = 0
            while time.monotonic() < deadline:
                lines = progress.read_text().splitlines()
                batches_seen = len(lines)
                if batches_seen >= 12:
                    break
                if proc.poll() is not None:
                    pytest.fail(
                        f"child died early: {proc.stderr.read()[-2000:]}"
                    )
                time.sleep(0.05)
            assert batches_seen >= 12, "child too slow to make traffic"
            os.kill(proc.pid, signal.SIGKILL)
        finally:
            proc.wait(timeout=30)

        # truth from the progress journal: b1 lines recorded; the device
        # may be up to one batch ahead (killed between launch and journal)
        lines = progress.read_text().splitlines()
        b1 = len(lines)
        last_batch, last_after = json.loads(lines[-1])
        assert last_after == last_batch + 1  # journal is per-batch counters

        out = subprocess.run(
            [sys.executable, str(child_py), snap_dir, str(progress), "restore"],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        result = json.loads(out.stdout)
        assert result["restored"]["restored"] == 8  # all 8 key rows warm
        finals = result["final"]
        assert len(set(finals)) == 1  # every key saw identical traffic
        final = finals[0]
        # bounded loss: the crash forgot at most one snapshot interval
        # (5 batches) of traffic...
        assert final >= b1 + 20 - 5, (final, b1)
        # ...and never invented traffic (true total is b1 or b1+1: the
        # kill can land between the device update and the journal write)
        assert final <= b1 + 1 + 20, (final, b1)
