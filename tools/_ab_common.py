"""Shared fixtures for the engine A/B tools (engine_ab.py, engine_ab2.py).

The two tools' numbers are cited against each other, so their workloads
must be IDENTICAL by construction: same fingerprint expansion, same Zipf id
staging, same CPU downscale fallback, same pinned `now` literal (a wall
clock `now` would make reruns non-reproducible and the pair non-comparable).
"""

from __future__ import annotations

import numpy as np

NOW_LIT = 1_700_000_000


def downscale(args, platform: str) -> None:
    """Shrink shapes in place for CPU smoke runs."""
    if platform != "tpu" and args.batch > (1 << 14):
        args.batch, args.slots, args.keys = 1 << 13, 1 << 18, 100_000


def make_expand():
    """Returns the on-device id -> SlabBatch expansion (two independent
    murmur-finalizer bijections; unit-second windows, limit 100)."""
    import jax.numpy as jnp

    from api_ratelimit_tpu.ops.slab import SlabBatch

    def fmix(x):
        x = x ^ (x >> 16)
        x = x * jnp.uint32(0x85EBCA6B)
        x = x ^ (x >> 13)
        x = x * jnp.uint32(0xC2B2AE35)
        return x ^ (x >> 16)

    def expand(ids):
        return SlabBatch(
            fp_lo=fmix(ids),
            fp_hi=fmix(ids ^ jnp.uint32(0x9E3779B9)),
            hits=jnp.ones_like(ids),
            limit=jnp.full_like(ids, 100),
            divider=jnp.full_like(ids, 1).astype(jnp.int32),
            jitter=jnp.zeros_like(ids).astype(jnp.int32),
        )

    return expand


def stage_zipf_ids(device, batch: int, n_keys: int, count: int, seed: int = 0):
    """`count` distinct Zipf(1.1) id arrays staged in device HBM."""
    import jax

    rng = np.random.RandomState(seed)
    ids_all = (
        rng.zipf(1.1, size=batch * count).astype(np.uint64) % n_keys
    ).astype(np.uint32).reshape(count, batch)
    staged = [jax.device_put(ids_all[i], device) for i in range(count)]
    for s in staged:
        s.block_until_ready()
    return staged
