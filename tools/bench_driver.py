"""Hardware-gated bench driver: probe → arm → staged run → harvest.

tools/chipwatch.py proved the shape on flaky chip windows: probe the
hardware, run only the stages that hardware can actually witness, bound
every stage with a subprocess timeout that kills the whole descendant
tree, and harvest evidence in one pass. This module generalizes that
from "is the TPU tunnel up" to the full regime question every BENCH
round since r07 has tripped over: **what can this box prove?** A 1-core
box running the FRONTEND_PROCS sweep produces numbers that look like a
scaling regression and are actually just the scheduler time-slicing one
core (BENCH_r11/r13 carry that caveat as prose). The fix is structural:

  * ``probe_hardware()`` detects host_cpus, JAX platform, and device
    count in a subprocess (a wedged device stack can't hang the driver);
  * ``arm_tiers()`` maps that onto the tier matrix — multi-process tiers
    (service_mp / cluster_scale / failover_blip / fleet_saturation /
    fed_divergence) arm
    only when ``host_cpus > 1``, device tiers (pallas slab, device
    sketch, multichip mesh) only when a chip window is open — and every
    un-armed tier is recorded **skipped-with-reason**, never as a
    misleading number;
  * ``cpu_affinity_plan()`` pins each spawned process to its own CPU
    slice when arming succeeds, so "procs=4" means four cores, not four
    names for one core;
  * the staged runner (shared with chipwatch) executes bench.py / the
    fleet-saturation tier under per-stage timeouts and harvests the last
    complete JSON line, validated by tools/bench_lint.py before it is
    allowed to become a BENCH_r*.json.

The ``--fleet`` mode is the distributed-load tier: it boots the real
FRONTEND_PROCS fleet (cmd/service_cmd.py — N frontend processes +
device owner + master aggregator), saturates it with tools/loadgen.py
(M driver processes, each its own GIL, merged client-side histograms),
and pairs the client view with the server-side fleet scrape
(``GET /metrics?fleet=1`` via stats/fleet.py). On a 1-core box it emits
the skipped-with-reason artifact instead — the acceptance shape.

The ``--fed-divergence`` mode is the global-quota-federation tier
(cluster/federation.py): two in-process cluster coordinators exchange
quota shares over real sockets under skewed closed-loop load, a mid-run
partition cuts the link both ways, and the artifact reports the measured
global overshoot against the share-ledger bound (overshoot ≤ reclaimed
unsettled tokens ≤ shares outstanding at the cut). On a 1-core box it
emits the skipped-with-reason artifact instead.

Usage:
    python -m tools.bench_driver [--out BENCH_rNN.json] [--budget S]
    python -m tools.bench_driver --fleet [--out FLEET_rNN.json]
    python -m tools.bench_driver --fed-divergence [--out FED_rNN.json]
    python -m tools.bench_driver --probe-only   # print hw + arming matrix
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from api_ratelimit_tpu.utils import provenance

# ---------------------------------------------------------------------------
# hardware probe


# Same discipline as chipwatch.PROBE_CMD: re-assert the env exactly like
# the measured stages do, then ask jax, and only trust the LAST line —
# plugin banners mentioning "tpu" must not arm device tiers.
PROBE_SRC = (
    "from api_ratelimit_tpu.utils.jaxsetup import respect_jax_platforms_env;"
    "respect_jax_platforms_env();"
    "import jax; d = jax.devices();"
    "print(d[0].platform, len(d))"
)


def probe_hardware(timeout_s: float = 90.0) -> dict:
    """Detect the regime: host_cpus (affinity mask), JAX platform, and
    device count. The device probe runs in a subprocess so a wedged
    tunnel times out here instead of hanging the driver; BENCH_PLATFORM
    short-circuits it the same way it short-circuits bench.py's own
    resolve_platform (forced runs must not pay a probe)."""
    hw = {
        "host_cpus": provenance.host_cpus(),
        "platform": "cpu",
        "device_count": 1,
        "probe": "",
    }
    forced = os.environ.get("BENCH_PLATFORM", "").strip().lower()
    if forced:
        hw["platform"] = forced
        hw["probe"] = "forced by BENCH_PLATFORM"
        return hw
    try:
        out = subprocess.run(
            [sys.executable, "-c", PROBE_SRC],
            cwd=REPO,
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
        lines = [ln.strip() for ln in out.stdout.splitlines() if ln.strip()]
        parts = lines[-1].split() if lines else []
        if out.returncode == 0 and len(parts) == 2 and parts[1].isdigit():
            hw["platform"] = parts[0]
            hw["device_count"] = int(parts[1])
            hw["probe"] = "subprocess probe ok"
        else:
            hw["probe"] = f"probe rc={out.returncode}; defaulting to cpu"
    except (OSError, subprocess.SubprocessError) as e:
        hw["probe"] = f"probe failed ({type(e).__name__}); defaulting to cpu"
    return hw


# ---------------------------------------------------------------------------
# tier arming

# Requirements a tier must meet before its number means anything.
# min_host_cpus=2 marks the multi-PROCESS tiers: on one core the procs
# time-slice and the sweep measures the scheduler, not the architecture.
# platform="tpu" marks the tiers that only exist on a real chip (the
# interpret-mode Pallas fallback validates shapes, not throughput).
# Device tiers: sharded arms on EITHER devices>=2 (real mesh) OR
# host_cpus>=2 (virtual CPU mesh in a subprocess — shape validation
# needs a second core to not starve the tier sweep above it).
TIER_REQUIREMENTS: dict = {
    "service_mp": {"min_host_cpus": 2},
    "cluster_scale": {"min_host_cpus": 2},
    "failover_blip": {"min_host_cpus": 2},
    "fleet_saturation": {"min_host_cpus": 2},
    "fed_divergence": {"min_host_cpus": 2},
    "sharded": {"min_host_cpus": 2, "or_min_devices": 2},
    # the victim tier is host RAM + numpy on the dispatch path: the
    # overload differential is meaningful on any box, so the tier always
    # arms — it is in the matrix so the artifact records that it RAN
    # (bench_lint's claim-honesty rules key off configs.keyspace_overload)
    "keyspace_overload": {},
    # routed-batching / hot-tier A/B: the padding-waste and false_over
    # columns are exact on any box (host-side routing + differential
    # fuzz), so the tier always arms — the rate columns only mean
    # parallel throughput on tpu+>=2 devices, where the tier's multichip
    # sub-key records that it ran on real chips (it rides the same
    # hardware gate as multichip_mesh)
    "sharded_zipf": {},
    "pallas_slab": {"platform": "tpu"},
    "device_sketch": {"platform": "tpu"},
    "multichip_mesh": {"platform": "tpu", "min_devices": 2},
}


def arm_tiers(hw: dict, force: str | None = None) -> dict:
    """Map probed hardware onto the tier matrix. Returns an ordered
    ``{tier: {"armed": bool, "reason": str}}`` — the reason string is
    part of the artifact contract (skipped tiers carry it verbatim), so
    it always names the failed requirement with the observed value,
    e.g. ``"host_cpus=1 < 2 (multi-process tier needs real cores)"``.

    ``force`` (the BENCH_ARM env knob) is "all" or a CSV of tier names:
    forced tiers arm regardless of hardware, with the force recorded as
    the reason — a forced run is visibly a forced run."""
    forced = set()
    if force:
        forced = (
            set(TIER_REQUIREMENTS)
            if force.strip().lower() == "all"
            else {t.strip() for t in force.split(",") if t.strip()}
        )
    cpus = int(hw.get("host_cpus", 1))
    devs = int(hw.get("device_count", 1))
    platform = str(hw.get("platform", "cpu"))
    out: dict = {}
    for tier, req in TIER_REQUIREMENTS.items():
        if tier in forced:
            out[tier] = {"armed": True, "reason": "forced by BENCH_ARM"}
            continue
        reasons = []
        min_cpus = req.get("min_host_cpus")
        if min_cpus and cpus < min_cpus:
            reasons.append(
                f"host_cpus={cpus} < {min_cpus} "
                f"(multi-process tier needs real cores)"
            )
        want = req.get("platform")
        if want and platform != want:
            reasons.append(f"platform={platform} != {want} (no chip window)")
        min_devs = req.get("min_devices")
        if min_devs and devs < min_devs:
            reasons.append(f"device_count={devs} < {min_devs}")
        or_devs = req.get("or_min_devices")
        if reasons and or_devs and devs >= or_devs:
            reasons = []  # the device path satisfies the tier on its own
        if reasons:
            out[tier] = {"armed": False, "reason": "; ".join(reasons)}
        else:
            out[tier] = {
                "armed": True,
                "reason": (
                    f"host_cpus={cpus} devices={devs} platform={platform}"
                ),
            }
    return out


# ---------------------------------------------------------------------------
# CPU affinity

AFFINITY_ENV = "BENCH_CPU_AFFINITY"


def cpu_affinity_plan(host_cpus: int, procs: int) -> list | None:
    """Partition the CPU inventory round-robin across ``procs`` spawned
    processes: ``[[0, 2], [1, 3]]`` for 4 cpus / 2 procs. Returns None
    when the box cannot give each process at least part of a distinct
    core story (host_cpus < 2) — pinning everything to cpu 0 would just
    add syscalls to the time-slicing the skip-reason already names."""
    if host_cpus < 2 or procs < 1:
        return None
    try:
        inventory = sorted(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        inventory = list(range(host_cpus))
    inventory = inventory[:host_cpus] or list(range(host_cpus))
    plan: list = [[] for _ in range(procs)]
    for i, cpu in enumerate(inventory):
        plan[i % procs].append(cpu)
    # more procs than cpus: wrap so every proc gets a pin (2 procs on
    # cpu 0 is still better than 2 procs floating over both cores while
    # 2 are pinned)
    for i in range(len(inventory), procs):
        plan[i] = plan[i % len(inventory)][:]
    return plan


def affinity_env(cpus) -> str:
    """Render one process's CPU slice for the child-side env knob."""
    return ",".join(str(c) for c in cpus)


def apply_affinity_from_env(env_var: str = AFFINITY_ENV) -> bool:
    """Child-side: pin this process to the CPU set named in ``env_var``
    (comma-separated ids). Returns True when a pin was applied. Invalid
    or unsupported masks are ignored — affinity is an arming refinement,
    never a reason a measurement child dies."""
    spec = os.environ.get(env_var, "").strip()
    if not spec:
        return False
    try:
        cpus = {int(c) for c in spec.split(",") if c.strip()}
        if cpus:
            os.sched_setaffinity(0, cpus)
            return True
    except (AttributeError, ValueError, OSError):
        pass
    return False


# ---------------------------------------------------------------------------
# staged subprocess machinery (generalized from tools/chipwatch.py; the
# chipwatch chain now delegates here)


def log(msg: str, prefix: str = "bench_driver") -> None:
    print(f"[{prefix} {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def descendants(root: int) -> list:
    """All live PIDs whose parent chain reaches `root` (/proc walk).

    killpg alone is not enough here: intermediate wrapper processes can
    re-group children, so a timed-out stage's grandchildren (bench
    sidecar workers, fleet frontends, pytest children) may sit in a
    different process group while still holding the device runtime."""
    ppid: dict = {}
    for ent in os.listdir("/proc"):
        if not ent.isdigit():
            continue
        try:
            with open(f"/proc/{ent}/stat") as f:
                ppid[int(ent)] = int(f.read().rsplit(")", 1)[1].split()[1])
        except (OSError, ValueError, IndexError):
            continue
    out, frontier = [], {root}
    while frontier:
        nxt = {p for p, pp in ppid.items() if pp in frontier and p not in out}
        out.extend(nxt)
        frontier = nxt
    return out


def kill_tree(pid: int) -> None:
    # Snapshot descendants BEFORE killing: the moment the direct child
    # dies, its children reparent to init and the PPID walk can no
    # longer find them.
    victims = descendants(pid)
    try:
        os.killpg(pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
    for p in victims + descendants(pid):
        try:
            os.kill(p, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


def run_stage(
    name: str,
    argv: list,
    timeout_s: float,
    marker: str,
    env: dict | None = None,
    log_path: str | None = None,
    log_prefix: str = "bench_driver",
) -> str:
    """One bounded stage: rc + marker classified into
    "ok" | "fail" | "timeout" | "fallback" (rc==0 WITHOUT the marker —
    the tool silently downscaled onto a fallback path, which is a
    window/arming problem, not success). Output appends to ``log_path``
    and the marker search is scoped to the bytes THIS run appended, so a
    marker left by a previous run never satisfies this one."""
    log(f"stage {name}: start (timeout {timeout_s:.0f}s)", log_prefix)
    if log_path is None:
        log_path = f"/tmp/chip_{name}.log"
    if env is None:
        env = dict(os.environ)
    offset = os.path.getsize(log_path) if os.path.exists(log_path) else 0
    with open(log_path, "ab") as lf:
        lf.write(f"\n===== {time.ctime()} =====\n".encode())
        lf.flush()
        try:
            # New session so a timeout can kill grandchildren too — an
            # orphan holding the device runtime would wedge every later
            # probe in this driver.
            proc = subprocess.Popen(
                argv,
                cwd=REPO,
                stdout=lf,
                stderr=subprocess.STDOUT,
                env=env,
                start_new_session=True,
            )
            rc = proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            kill_tree(proc.pid)
            proc.wait()
            log(
                f"stage {name}: TIMEOUT after {timeout_s:.0f}s (log {log_path})",
                log_prefix,
            )
            return "timeout"
    with open(log_path, "rb") as f:
        f.seek(offset)
        appended = f.read().decode(errors="replace")
    ok = rc == 0 and marker in appended
    log(
        f"stage {name}: rc={rc} marker_found={marker in appended} "
        f"(log {log_path})",
        log_prefix,
    )
    if ok:
        return "ok"
    return "fail" if rc != 0 else "fallback"


def harvest_json_line(log_path: str, offset: int = 0) -> dict | None:
    """The artifact contract bench.py has honored since round 3: the last
    COMPLETE (newline-terminated) JSON line on stdout is the artifact."""
    try:
        with open(log_path, "rb") as f:
            f.seek(offset)
            text = f.read().decode(errors="replace")
    except OSError:
        return None
    complete = text[: text.rfind("\n") + 1]
    lines = [ln for ln in complete.splitlines() if ln.startswith("{")]
    for line in reversed(lines):
        try:
            return json.loads(line)
        except ValueError:
            continue
    return None


# ---------------------------------------------------------------------------
# fleet saturation tier (--fleet)


def _http_ok(url: str, timeout: float = 1.0) -> bool:
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:  # noqa: S310
            return resp.status == 200
    except Exception:  # noqa: BLE001 - readiness poll
        return False


_FLEET_CONFIG = """\
domain: bench
descriptors:
  - key: api_key
    rate_limit:
      unit: second
      requests_per_unit: 1000000
"""


def run_fleet_saturation(hw: dict, arming: dict, budget_s: float) -> dict:
    """The distributed-load tier: boot the real FRONTEND_PROCS fleet,
    saturate it with tools/loadgen.py driver processes, and pair the
    merged client histograms with the server-side fleet scrape deltas.
    Armed only when host_cpus > 1 — the caller records the skip."""
    from tools import loadgen

    procs = int(os.environ.get("BENCH_FLEET_PROCS", "0") or 0) or min(
        4, max(2, hw["host_cpus"] // 2)
    )
    drivers = int(os.environ.get("BENCH_FLEET_DRIVERS", "2"))
    duration = float(os.environ.get("BENCH_FLEET_SECONDS", "5"))
    port = int(os.environ.get("BENCH_FLEET_PORT", "18080"))
    debug_port = int(os.environ.get("BENCH_FLEET_DEBUG_PORT", "16070"))
    result: dict = {
        "frontend_procs": procs,
        "driver_procs": drivers,
        "duration_s": duration,
    }
    td = tempfile.mkdtemp(prefix="bench-fleet-")
    config_dir = os.path.join(td, "current", "ratelimit", "config")
    os.makedirs(config_dir)
    with open(os.path.join(config_dir, "bench.yaml"), "w") as f:
        f.write(_FLEET_CONFIG)
    env = dict(os.environ)
    env.update(
        {
            "FRONTEND_PROCS": str(procs),
            "RUNTIME_ROOT": os.path.join(td, "current"),
            "RUNTIME_SUBDIRECTORY": "ratelimit",
            "BACKEND_TYPE": "tpu",
            "JAX_PLATFORMS": env.get("JAX_PLATFORMS", "cpu"),
            "PORT": str(port),
            "GRPC_PORT": str(port + 1),
            "DEBUG_PORT": str(debug_port),
            "USE_STATSD": "false",
            "SIDECAR_SOCKET": os.path.join(td, "owner.sock"),
            "LOG_LEVEL": "WARNING",
        }
    )
    env.pop("XLA_FLAGS", None)
    # pin each frontend worker + the owner to its own CPU slice: the
    # master passes the slice down via the env knob the Runner applies
    plan = cpu_affinity_plan(hw["host_cpus"], procs + 1)
    if plan is not None:
        env["BENCH_CPU_AFFINITY_PLAN"] = "|".join(
            affinity_env(cpus) for cpus in plan
        )
    master = subprocess.Popen(
        [sys.executable, "-m", "api_ratelimit_tpu.cmd.service_cmd"],
        env=env,
        cwd=REPO,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.STDOUT,
        start_new_session=True,
    )
    try:
        deadline = time.monotonic() + min(budget_s * 0.5, 180.0)
        while not _http_ok(f"http://127.0.0.1:{port}/healthcheck"):
            if master.poll() is not None:
                raise RuntimeError(
                    f"fleet master exited rc={master.returncode} before ready"
                )
            if time.monotonic() > deadline:
                raise TimeoutError("fleet never became healthy")
            time.sleep(0.25)
        fleet_url = f"http://127.0.0.1:{debug_port}/metrics?fleet=1"
        report = loadgen.run_distributed(
            url=f"http://127.0.0.1:{port}/json",
            procs=drivers,
            threads=int(os.environ.get("BENCH_FLEET_THREADS", "4")),
            duration_s=duration,
            domain="bench",
            key="api_key",
            n_keys=int(os.environ.get("BENCH_FLEET_KEYS", "512")),
            fleet_metrics_url=fleet_url,
        )
        result.update(report)
    finally:
        kill_tree(master.pid)
        master.wait()
    return result


# ---------------------------------------------------------------------------
# federation divergence tier (--fed-divergence)


def run_fed_divergence(hw: dict, arming: dict, budget_s: float) -> dict:
    """The bounded-divergence tier (cluster/federation.py): two in-process
    cluster coordinators exchange shares over real TCP sockets under
    closed-loop Zipf-skewed load, a mid-run partition cuts the WAN both
    ways, and the measured global overshoot is checked against the
    share-ledger bound — overshoot ≤ reclaimed unsettled tokens ≤ the
    shares outstanding at the partition instant. Armed only when
    host_cpus > 1 (two live closed loops plus two settle pumps on one
    core measure the scheduler, not the algebra)."""
    import random
    import socket
    import threading

    from api_ratelimit_tpu.backends import sidecar as sc
    from api_ratelimit_tpu.cluster.federation import FederationCoordinator
    from api_ratelimit_tpu.utils.timeutil import RealTimeSource

    duration = min(
        float(os.environ.get("BENCH_FED_SECONDS", "6")), budget_s * 0.8
    )
    n_keys = int(os.environ.get("BENCH_FED_KEYS", "48"))
    limit = int(os.environ.get("BENCH_FED_LIMIT", "400"))

    # two listeners bound first (the membership map needs the ports),
    # coordinators second, accept loops last
    socks = {}
    for name in ("east", "west"):
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        srv.listen(32)
        srv.settimeout(0.2)
        socks[name] = srv
    peers = {
        name: f"tcp://127.0.0.1:{srv.getsockname()[1]}"
        for name, srv in socks.items()
    }
    coords = {
        name: FederationCoordinator(
            name,
            peers,
            time_source=RealTimeSource(),
            share_min=8,
            share_max=256,
            settle_interval_ms=50.0,
            max_lag_ms=250.0,
            share_ttl_ms=600.0,
        )
        for name in socks
    }
    partitioned = threading.Event()
    closing = threading.Event()

    def accept_loop(name: str) -> None:
        srv = socks[name]
        while not closing.is_set():
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            if partitioned.is_set():
                conn.close()  # the WAN cut: peers get connection reset
                continue

            def serve(c=conn, coord=coords[name]) -> None:
                try:
                    need = sc._HDR.size
                    buf = b""
                    while len(buf) < need:
                        chunk = c.recv(need - len(buf))
                        if not chunk:
                            return
                        buf += chunk
                    coord.serve_exchange(c)
                except Exception:  # noqa: BLE001 - chaos by design
                    pass
                finally:
                    c.close()

            threading.Thread(target=serve, daemon=True).start()

    threads = [
        threading.Thread(target=accept_loop, args=(n,), daemon=True)
        for n in socks
    ]
    for t in threads:
        t.start()

    # Zipf-ish key popularity, skewed differently per region: east's hot
    # head is west's tail — the cross-borrow traffic that makes shares
    # flow both directions
    rng = random.Random(1234)
    now = int(time.time())
    window = (now // 3600) * 3600
    deadline = window + 3600
    keys = [((rng.getrandbits(63) << 1) | (i & 1), window) for i in range(n_keys)]
    weights = [1.0 / (i + 1) for i in range(n_keys)]
    east_keys = random.Random(7).choices(keys, weights=weights, k=4096)
    west_keys = random.Random(11).choices(
        keys, weights=list(reversed(weights)), k=4096
    )

    admitted: dict = {k: 0 for k in keys}
    denied = {"east": 0, "west": 0}
    lock = threading.Lock()
    t_end = time.monotonic() + duration
    t_cut = time.monotonic() + duration * 0.35
    t_heal = time.monotonic() + duration * 0.75
    bound_at_cut = {"tokens": -1}

    def drive(name: str, plan: list) -> None:
        coord = coords[name]
        i = 0
        next_pump = 0.0
        while time.monotonic() < t_end:
            fp, win = plan[i % len(plan)]
            i += 1
            ok = coord.consume(fp, win, limit, 1, deadline=deadline)
            with lock:
                if ok:
                    admitted[(fp, win)] += 1
                else:
                    denied[name] += 1
            t = time.monotonic()
            if t >= next_pump:
                next_pump = t + 0.05
                try:
                    coord.pump()
                except Exception:  # noqa: BLE001 - partition chaos
                    pass
            if i % 64 == 0:
                time.sleep(0.001)

    drivers = [
        threading.Thread(target=drive, args=("east", east_keys), daemon=True),
        threading.Thread(target=drive, args=("west", west_keys), daemon=True),
    ]
    for d in drivers:
        d.start()
    healed_at = None
    while time.monotonic() < t_end:
        t = time.monotonic()
        if not partitioned.is_set() and t >= t_cut and t < t_heal:
            bound_at_cut["tokens"] = sum(
                c.outstanding_tokens() for c in coords.values()
            )
            partitioned.set()
            log(
                f"fed tier: partition cut — outstanding "
                f"{bound_at_cut['tokens']} tokens"
            )
        if partitioned.is_set() and t >= t_heal:
            partitioned.clear()
            healed_at = t
            log("fed tier: partition healed")
        time.sleep(0.02)
    for d in drivers:
        d.join(timeout=10.0)
    # post-run settle passes so the healed ledgers reconverge
    for _ in range(6):
        for c in coords.values():
            try:
                c.pump()
            except Exception:  # noqa: BLE001
                pass
        time.sleep(0.06)
    closing.set()
    for srv in socks.values():
        srv.close()
    for c in coords.values():
        c.close()

    overshoot = sum(max(0, n - limit) for n in admitted.values())
    reclaimed = sum(c.reclaimed_tokens_total for c in coords.values())
    stale = sum(c.stale_epoch_rejected_total for c in coords.values())
    result = {
        "clusters": sorted(coords),
        "keys": n_keys,
        "per_key_limit": limit,
        "duration_s": duration,
        "admitted_total": sum(admitted.values()),
        "denied_total": dict(denied),
        "overshoot_tokens": overshoot,
        "reclaimed_tokens": reclaimed,
        "outstanding_at_partition": bound_at_cut["tokens"],
        # the ledger invariant (cluster/federation.py): every admitted
        # token beyond the limit traces to a reclaimed-but-still-spendable
        # share — idle TTL reclaims count too, so the bound is reclaimed
        # tokens, with the partition-instant outstanding as context
        "within_bound": overshoot <= reclaimed,
        "stale_epoch_rejected": stale,
        "healed": healed_at is not None,
        "settles": {
            n: c.settles_total for n, c in coords.items()
        },
        "grants": {n: c.grants_total for n, c in coords.items()},
        "degraded_during_run": {
            n: bool(c.degraded or c.exchange_errors_total)
            for n, c in coords.items()
        },
    }
    return result


# ---------------------------------------------------------------------------
# driver CLI


def _stamp(doc: dict, hw: dict, arming: dict) -> dict:
    doc["provenance"] = provenance.build_provenance(
        hw["platform"], hw["device_count"]
    )
    doc["tiers"] = arming
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", help="write the harvested artifact here")
    ap.add_argument(
        "--budget", type=float,
        default=float(os.environ.get("BENCH_BUDGET_S", "480")),
    )
    ap.add_argument(
        "--probe-only", action="store_true",
        help="print the hardware + arming matrix and exit",
    )
    ap.add_argument(
        "--fleet", action="store_true",
        help="run the fleet-saturation tier instead of bench.py",
    )
    ap.add_argument(
        "--fed-divergence", action="store_true",
        help="run the federation bounded-divergence tier instead of "
        "bench.py",
    )
    args = ap.parse_args(argv)

    hw = probe_hardware()
    arming = arm_tiers(hw, force=os.environ.get("BENCH_ARM"))
    log(f"hardware: {hw}")
    for tier, st in arming.items():
        log(f"tier {tier}: {'ARMED' if st['armed'] else 'skip'} — {st['reason']}")

    if args.probe_only:
        print(json.dumps({"hardware": hw, "tiers": arming}, indent=2))
        return 0

    if args.fed_divergence:
        doc: dict = {"metric": "fed_divergence", "hardware": hw}
        st = arming["fed_divergence"]
        if not st["armed"]:
            doc["fed_divergence"] = {"skipped": st["reason"]}
        else:
            try:
                doc["fed_divergence"] = run_fed_divergence(
                    hw, arming, args.budget
                )
            except Exception as e:  # noqa: BLE001 - artifact must land
                doc["fed_divergence"] = {"error": str(e)[-300:]}
        _stamp(doc, hw, arming)
        line = json.dumps(doc)
        print(line, flush=True)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        return 0

    if args.fleet:
        doc: dict = {"metric": "fleet_saturation", "hardware": hw}
        st = arming["fleet_saturation"]
        if not st["armed"]:
            doc["fleet_saturation"] = {"skipped": st["reason"]}
        else:
            try:
                doc["fleet_saturation"] = run_fleet_saturation(
                    hw, arming, args.budget
                )
            except Exception as e:  # noqa: BLE001 - artifact must land
                doc["fleet_saturation"] = {"error": str(e)[-300:]}
        _stamp(doc, hw, arming)
        line = json.dumps(doc)
        print(line, flush=True)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        return 0

    # staged bench.py run, chipwatch-style: the stage timeout must exceed
    # bench's own forced-emit horizon (budget + 120s watchdog + init
    # slack) or we SIGKILL the tree before the watchdog lands the line
    env = dict(os.environ)
    env.setdefault("BENCH_PLATFORM", hw["platform"])
    env.setdefault("BENCH_BUDGET_S", str(int(args.budget)))
    stage_log = os.path.join(
        tempfile.gettempdir(), "bench_driver_bench.log"
    )
    offset = os.path.getsize(stage_log) if os.path.exists(stage_log) else 0
    outcome = run_stage(
        "bench",
        [sys.executable, "bench.py"],
        args.budget + 300.0,
        '"configs"',
        env=env,
        log_path=stage_log,
    )
    doc = harvest_json_line(stage_log, offset)
    if doc is None:
        log(f"no artifact line harvested (outcome={outcome})")
        return 1
    if "provenance" not in doc:
        # belt-and-braces: bench.py stamps its own block; a legacy bench
        # on this path still leaves the driver's stamp
        _stamp(doc, hw, arming)
    from tools import bench_lint

    findings = bench_lint.lint_artifact(doc)
    for f_ in findings:
        log(f"bench_lint: {f_}")
    line = json.dumps(doc)
    print(line, flush=True)
    if args.out and not findings:
        with open(args.out, "w") as f:
            f.write(line + "\n")
        log(f"artifact written to {args.out}")
    elif args.out:
        log(f"artifact NOT written to {args.out}: {len(findings)} lint finding(s)")
        return 1
    return 0 if outcome in ("ok", "fallback") and not findings else 1


if __name__ == "__main__":
    sys.exit(main())
