"""Lint BENCH artifact schema — the sibling of tools/metrics_lint.py.

A BENCH_r*.json row is a claim; this linter is what keeps claims
honest before they enter the trajectory that tools/bench_report.py
renders. It fails on:

  * provenance-free rows: a stamped artifact must carry the CRC'd
    provenance block (api_ratelimit_tpu/utils/provenance.py) and the
    block must verify — a hand-edited or truncated block is a finding;
  * bare skips: every ``{"skipped": ...}`` marker anywhere in the
    artifact must carry a non-empty reason string ("budget",
    "host_cpus=1 < 2 ...") — a tier that silently didn't run reads as
    a tier that ran;
  * empty evidence: a service tier that claims a rate must carry its
    stage histogram block with a positive request count;
  * arming drift: when the artifact carries a tier-arming matrix, every
    un-armed tier that appears in configs must actually be skip- or
    error-marked, not carry numbers a disarmed tier cannot have earned;
  * chaos-claim drift: a CHAOS_rNN.json campaign artifact (kind
    "chaos", tools/chaos_campaign.py) must pin every seed's
    timeline_crc, cover every composed nemesis class (or skip it with
    a reason), and carry the FULL violation reports in agreement with
    its verdict.

``--legacy`` relaxes the provenance requirement for pre-round-16
artifacts (BENCH_r01..r15 predate the stamp); everything else still
applies, which is how the old rows stay render-able by bench_report
without being silently trusted as comparable.

Run standalone (``python tools/bench_lint.py BENCH_r16.json``; exit 1
on findings) or via the tier-1 pytest wrapper. No jax import.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from api_ratelimit_tpu.utils import provenance

# every stamped bench.py artifact carries these; fleet artifacts carry
# their own metric name but the same stamp
REQUIRED_TOP = ("metric", "configs", "platform", "git_rev")


def _iter_skips(node, path=""):
    """Yield (path, reason) for every {"skipped": reason} marker."""
    if isinstance(node, dict):
        if "skipped" in node:
            yield path, node["skipped"]
        for k, v in node.items():
            if k != "skipped":
                yield from _iter_skips(v, f"{path}.{k}" if path else str(k))
    elif isinstance(node, list):
        for i, v in enumerate(node):
            yield from _iter_skips(v, f"{path}[{i}]")


def lint_artifact(doc: dict, require_provenance: bool = True) -> list:
    """Returns human-readable findings (empty = clean)."""
    findings: list = []
    if not isinstance(doc, dict):
        return ["artifact is not a JSON object"]

    # single-tier artifacts (--fleet / --fed-divergence) carry their own
    # metric name and body block instead of the bench.py configs shape
    is_fleet = doc.get("metric") in ("fleet_saturation", "fed_divergence")
    if not is_fleet:
        for field in REQUIRED_TOP:
            if field not in doc:
                findings.append(f"missing required top-level field {field!r}")

    block = doc.get("provenance")
    if require_provenance:
        if block is None:
            findings.append(
                "provenance block missing (run through bench.py/"
                "bench_driver, or lint with --legacy for pre-r16 rows)"
            )
        elif not provenance.verify(block):
            findings.append(
                "provenance block present but does not verify "
                "(missing fields or CRC mismatch)"
            )
        elif not is_fleet and doc.get("platform") and str(
            block.get("platform")
        ) != str(doc.get("platform")):
            findings.append(
                f"provenance platform {block.get('platform')!r} disagrees "
                f"with artifact platform {doc.get('platform')!r}"
            )

    # every skip marker must carry a real reason
    for path, reason in _iter_skips(doc):
        if not isinstance(reason, str) or not reason.strip():
            findings.append(
                f"{path or '<root>'}: skipped without a reason "
                f"(got {reason!r})"
            )

    # a service tier claiming a rate must carry non-empty stage evidence
    configs = doc.get("configs") or {}
    if isinstance(configs, dict):
        for tier, body in configs.items():
            if not isinstance(body, dict) or "rate" not in body:
                continue
            stages = body.get("stages")
            if stages is None:
                continue  # engine-level tiers have no stage split
            if not isinstance(stages, dict) or not stages:
                findings.append(
                    f"configs.{tier}: rate claimed but stages block empty"
                )
                continue
            count = body.get("n") or stages.get("count") or next(
                (
                    v.get("count")
                    for v in stages.values()
                    if isinstance(v, dict) and v.get("count")
                ),
                None,
            )
            if not count:
                findings.append(
                    f"configs.{tier}: rate claimed but no positive request "
                    f"count in stages"
                )

    # claim honesty for the federation tier: a row that actually ran must
    # carry the numeric divergence evidence (the overshoot and its bound),
    # not just a verdict — "within_bound": true with no numbers reads as
    # a measurement that never happened
    if doc.get("metric") == "fed_divergence":
        body = doc.get("fed_divergence")
        if not isinstance(body, dict):
            findings.append("fed_divergence: missing tier body block")
        elif "skipped" not in body and "error" not in body:
            for field in (
                "overshoot_tokens",
                "reclaimed_tokens",
                "admitted_total",
                "within_bound",
            ):
                if field == "within_bound":
                    if not isinstance(body.get(field), bool):
                        findings.append(
                            f"fed_divergence.{field}: missing or non-bool "
                            f"bound verdict"
                        )
                elif not isinstance(body.get(field), (int, float)):
                    findings.append(
                        f"fed_divergence.{field}: ran but carries no "
                        f"numeric value"
                    )

    # claim honesty for the victim-tier overload sweep: a tier-on row
    # that claims a false-admit count must carry the stated bound's loss
    # terms (slab HEALTH drops + the tier's overflow ledger) and the
    # bound verdict — "false_admits": 0 without the ledger it is bounded
    # against reads as a claim, not a measurement
    ks = configs.get("keyspace_overload") if isinstance(configs, dict) else None
    if isinstance(ks, dict) and "skipped" not in ks and "error" not in ks:
        sweep = ks.get("sweep")
        if not isinstance(sweep, list) or not sweep:
            findings.append(
                "configs.keyspace_overload: ran but carries no sweep rows"
            )
        else:
            for i, srow in enumerate(sweep):
                if not isinstance(srow, dict) or "skipped" in srow or (
                    "error" in srow
                ):
                    continue
                on = srow.get("on")
                if not isinstance(on, dict):
                    findings.append(
                        f"configs.keyspace_overload.sweep[{i}]: ran "
                        f"without a tier-on arm"
                    )
                    continue
                if not isinstance(on.get("false_admits"), int):
                    findings.append(
                        f"configs.keyspace_overload.sweep[{i}].on: ran "
                        f"but carries no false-admit count"
                    )
                    continue
                for field in ("drops", "overflow_lost_count_sum"):
                    if not isinstance(on.get(field), (int, float)):
                        findings.append(
                            f"configs.keyspace_overload.sweep[{i}].on: "
                            f"false_admits claimed without bound term "
                            f"{field!r}"
                        )
                if not isinstance(on.get("bound_ok"), bool):
                    findings.append(
                        f"configs.keyspace_overload.sweep[{i}].on: "
                        f"false_admits claimed without the bound_ok "
                        f"verdict"
                    )

    # claim honesty for the hot-key tier (sharded_zipf): a hot-tier arm
    # that claims a rate or a speedup is a "split quotas don't over-admit"
    # claim, so the artifact must carry the differential-fuzz verdict —
    # false_over (int), the documented bound it was checked against, and
    # bound_ok. A speedup without the false_over verdict reads as "we
    # went faster by admitting traffic the limit forbids".
    sz = configs.get("sharded_zipf") if isinstance(configs, dict) else None
    if isinstance(sz, dict) and "skipped" not in sz and "error" not in sz:
        hot = sz.get("hot")
        if not isinstance(hot, dict):
            findings.append(
                "configs.sharded_zipf: ran but carries no hot-tier arm"
            )
        elif "skipped" not in hot and "error" not in hot and (
            hot.get("hot_rate") is not None or hot.get("speedup") is not None
        ):
            if not isinstance(hot.get("false_over"), int):
                findings.append(
                    "configs.sharded_zipf.hot: speedup claimed without "
                    "an integer false_over fuzz verdict"
                )
            if not isinstance(hot.get("false_over_bound"), (int, float)):
                findings.append(
                    "configs.sharded_zipf.hot: false_over without the "
                    "bound it was checked against (false_over_bound)"
                )
            if not isinstance(hot.get("bound_ok"), bool):
                findings.append(
                    "configs.sharded_zipf.hot: speedup claimed without "
                    "the bound_ok verdict"
                )

    # claim honesty for chaos campaigns (CHAOS_rNN.json, chaos/): a
    # campaign artifact is a "zero violations under composed nemeses"
    # claim, so it must carry the replay pins and the full evidence:
    #   * every composed nemesis class appears in coverage with a
    #     positive action count or an explicit skip reason — a class
    #     that silently drew nothing reads as a class that was tested;
    #   * every seed row pins its timeline_crc (the replay fingerprint)
    #     and its verdict;
    #   * the violations list is always present, carries every
    #     violating seed's full report, and agrees with the verdict —
    #     a violation must never be summarized away.
    if doc.get("kind") == "chaos":
        seeds = doc.get("seeds")
        if not isinstance(seeds, list) or not seeds:
            findings.append("chaos: missing or empty seeds block")
            seeds = []
        seed_verdicts = []
        for i, srow in enumerate(seeds):
            if not isinstance(srow, dict):
                findings.append(f"chaos: seeds[{i}] malformed")
                continue
            if not isinstance(srow.get("timeline_crc"), int):
                findings.append(
                    f"chaos: seeds[{i}] has no timeline_crc — the run "
                    f"cannot be replayed"
                )
            if srow.get("verdict") not in ("ok", "violation"):
                findings.append(
                    f"chaos: seeds[{i}] verdict must be ok|violation, "
                    f"got {srow.get('verdict')!r}"
                )
            seed_verdicts.append(srow.get("verdict"))
        composed = set()
        for cfg in doc.get("configs") or []:
            if isinstance(cfg, dict):
                composed.update(cfg.get("classes") or [])
        cov = doc.get("coverage")
        if not isinstance(cov, dict):
            findings.append("chaos: missing coverage block")
        else:
            for cls in sorted(composed):
                entry = cov.get(cls)
                if isinstance(entry, int) and entry > 0:
                    continue
                if isinstance(entry, dict) and "skipped" in entry:
                    continue  # reason quality enforced by _iter_skips
                findings.append(
                    f"chaos: coverage.{cls}: composed class has neither "
                    f"a positive action count nor a skip reason"
                )
        violations = doc.get("violations")
        if not isinstance(violations, list):
            findings.append("chaos: violations list missing")
        else:
            n_violating = sum(1 for v in seed_verdicts if v == "violation")
            if n_violating and not violations:
                findings.append(
                    "chaos: seed rows report violations but the "
                    "violations list is empty — reports were dropped"
                )
            want = "violation" if violations else "ok"
            if doc.get("verdict") != want:
                findings.append(
                    f"chaos: verdict {doc.get('verdict')!r} disagrees "
                    f"with the violations list ({len(violations)} entries)"
                )

    # arming drift: a disarmed tier must not carry numbers
    tiers = doc.get("tiers")
    if isinstance(tiers, dict):
        for tier, st in tiers.items():
            if not isinstance(st, dict):
                findings.append(f"tiers.{tier}: malformed arming entry")
                continue
            if "armed" not in st or not str(st.get("reason", "")).strip():
                findings.append(
                    f"tiers.{tier}: arming entry needs 'armed' and a "
                    f"non-empty 'reason'"
                )
                continue
            body = configs.get(tier) if isinstance(configs, dict) else None
            if (
                not st["armed"]
                and isinstance(body, dict)
                and "skipped" not in body
                and "error" not in body
            ):
                findings.append(
                    f"configs.{tier}: tier is disarmed "
                    f"({st['reason']}) but carries measurements"
                )
    return findings


def lint_file(path: str, require_provenance: bool = True) -> list:
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        return [f"{path}: unreadable ({e})"]
    lines = [ln for ln in text.splitlines() if ln.strip().startswith("{")]
    if not lines:
        return [f"{path}: no JSON line found"]
    try:
        doc = json.loads(lines[-1])
    except ValueError as e:
        return [f"{path}: last JSON line does not parse ({e})"]
    return [
        f"{path}: {finding}"
        for finding in lint_artifact(doc, require_provenance)
    ]


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    legacy = "--legacy" in argv
    paths = [a for a in argv if a != "--legacy"]
    if not paths:
        print("usage: bench_lint.py [--legacy] BENCH_rNN.json ...",
              file=sys.stderr)
        return 2
    findings: list = []
    for path in paths:
        findings.extend(lint_file(path, require_provenance=not legacy))
    if findings:
        for finding in findings:
            print(f"bench-lint: {finding}", file=sys.stderr)
        print(f"bench-lint: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    print(f"bench-lint: OK ({len(paths)} artifact(s) checked)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
