"""Render the BENCH_r*.json perf trajectory with comparability gating.

Every round's artifact is a claim taken in a hardware regime; comparing
rows across regimes is how the "~2.2x slower box" caveat PERF.md has
carried as prose since round 7 becomes a silent lie in a table. This
report makes the gate structural:

  * each round resolves to a **platform marker** — from the CRC'd
    provenance block when the row is stamped (round 16 onward,
    utils/provenance.py), or from the ``LEGACY_BOXES`` map for older
    rows (rounds 1–6 ran on the original bench box; rounds 7–15 on the
    replacement box measured ~2.2x slower on the same rev — the box
    swap is the reason the map exists);
  * the trajectory table prints every round with its marker, and the
    round-over-round delta column is only computed when BOTH markers
    match — a regime change prints an explicit ``not comparable`` line
    instead of a percentage;
  * ``--diff A B`` compares two rounds metric by metric and **refuses**
    (exit 2) when their markers differ — the acceptance behavior: you
    cannot diff r06 against r07 without forcing.

Usage:
    python -m tools.bench_report                 # trajectory table
    python -m tools.bench_report --json          # machine-readable
    python -m tools.bench_report --diff r11 r12  # gated pairwise diff

No jax import — reading evidence must never need an accelerator.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from api_ratelimit_tpu.utils import provenance

_ROUND_FILE = re.compile(r"^BENCH_r(\d+)\.json$")

# The box history behind pre-stamp rounds (PERF.md rounds 1-15): rounds
# 1-6 ran on the original 1-core bench box; from round 7 the environment
# moved to a replacement box that measured ~2.2x slower on an unchanged
# rev (PERF.md r07 "the box, not the code"). Markers deliberately do NOT
# collide with stamped markers (prefix "legacy/"), so an old row can
# never silently compare against a stamped one even on lookalike
# hardware — the legacy rows carry no cpu_model evidence to check.
LEGACY_BOXES = [
    (1, 6, "box-r01"),
    (7, 15, "box-r07-2.2x-slower"),
]


def _legacy_box(round_no: int) -> str:
    for lo, hi, name in LEGACY_BOXES:
        if lo <= round_no <= hi:
            return name
    return f"box-unknown-r{round_no:02d}"


def discover(repo: str = REPO) -> list:
    """All (round_no, path) pairs, sorted by round."""
    out = []
    for name in os.listdir(repo):
        m = _ROUND_FILE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(repo, name)))
    return sorted(out)


def load_artifact(path: str):
    """Parse one round file: whole-file JSON, else the last complete
    JSON line, else the artifact line embedded in a driver-wrapper
    ``tail`` field (rounds 1-5 are wrapper captures)."""
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return None
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
        for line in reversed(text.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    doc = json.loads(line)
                    break
                except ValueError:
                    continue
    if isinstance(doc, dict) and "tail" in doc and "configs" not in doc:
        # driver wrapper: the bench line is the last parseable JSON
        # object embedded in the captured tail
        for line in reversed(str(doc["tail"]).splitlines()):
            line = line.strip()
            if line.startswith('{"metric"'):
                try:
                    return json.loads(line)
                except ValueError:
                    continue
        return doc
    return doc


def marker_for(round_no: int, doc) -> dict:
    """Resolve one round's comparability marker. Stamped rows use the
    verified provenance block; unverifiable or legacy rows fall back to
    the box-history map and say so."""
    block = (doc or {}).get("provenance")
    if provenance.verify(block):
        return {
            "marker": provenance.platform_marker(block),
            "source": "stamped",
        }
    platform = (doc or {}).get("platform") or "?"
    return {
        "marker": f"legacy/{platform}/{_legacy_box(round_no)}",
        "source": (
            "legacy box map"
            if block is None
            else "legacy box map (provenance present but unverifiable)"
        ),
    }


def _count_skips(node) -> int:
    if isinstance(node, dict):
        return ("skipped" in node) + sum(
            _count_skips(v) for k, v in node.items() if k != "skipped"
        )
    if isinstance(node, list):
        return sum(_count_skips(v) for v in node)
    return 0


# the comparable headline metrics, as (label, extractor) pairs
def _metrics(doc: dict) -> dict:
    cfg = doc.get("configs") or {}
    eng = cfg.get("zipf_10M_engine") or {}
    flat = cfg.get("flat_per_second") or {}
    out = {}
    if isinstance(eng, dict) and isinstance(eng.get("rate"), (int, float)):
        out["engine_rate"] = eng["rate"]
    if isinstance(flat, dict):
        if isinstance(flat.get("rate"), (int, float)):
            out["flat_rate"] = flat["rate"]
        if isinstance(flat.get("p99_ms"), (int, float)):
            out["flat_p99_ms"] = flat["p99_ms"]
    return out


def build_rows(repo: str = REPO) -> list:
    rows = []
    for round_no, path in discover(repo):
        doc = load_artifact(path)
        entry = {
            "round": round_no,
            "file": os.path.basename(path),
            "parsed": isinstance(doc, dict),
        }
        if not isinstance(doc, dict):
            entry.update({"marker": "unparseable", "source": "none"})
            rows.append(entry)
            continue
        entry.update(marker_for(round_no, doc))
        entry["git_rev"] = doc.get("git_rev", "")
        entry["metrics"] = _metrics(doc)
        entry["skips"] = _count_skips(doc)
        rows.append(entry)
    return rows


def trajectory(rows: list) -> list:
    """Round-over-round comparisons, gated on marker equality. Each item
    is either a computed delta set or an explicit refusal."""
    out = []
    prev = None
    for row in rows:
        if not row["parsed"] or not row.get("metrics"):
            prev = None if not row["parsed"] else prev
            continue
        if prev is not None:
            if prev["marker"] != row["marker"]:
                out.append(
                    {
                        "from": prev["round"],
                        "to": row["round"],
                        "comparable": False,
                        "refusal": (
                            f"not comparable ({prev['marker']} vs "
                            f"{row['marker']})"
                        ),
                    }
                )
            else:
                deltas = {}
                for k, v in row["metrics"].items():
                    pv = prev["metrics"].get(k)
                    if isinstance(pv, (int, float)) and pv:
                        deltas[k] = round((v - pv) / pv * 100.0, 1)
                out.append(
                    {
                        "from": prev["round"],
                        "to": row["round"],
                        "comparable": True,
                        "delta_pct": deltas,
                    }
                )
        prev = row
    return out


def render(rows: list, comparisons: list) -> str:
    lines = []
    lines.append(
        f"{'round':>5}  {'rev':<8} {'engine_rate':>12} {'flat_rate':>10} "
        f"{'flat_p99':>9} {'skips':>5}  marker"
    )
    for row in rows:
        if not row["parsed"]:
            lines.append(
                f"{row['round']:>5}  {'-':<8} {'unparseable':>12} "
                f"{'-':>10} {'-':>9} {'-':>5}  {row['marker']}"
            )
            continue
        m = row.get("metrics", {})
        lines.append(
            f"{row['round']:>5}  {row.get('git_rev') or '-':<8} "
            f"{m.get('engine_rate', '-'):>12} {m.get('flat_rate', '-'):>10} "
            f"{m.get('flat_p99_ms', '-'):>9} {row.get('skips', 0):>5}  "
            f"{row['marker']} [{row['source']}]"
        )
    lines.append("")
    lines.append("round-over-round (marker-gated):")
    for c in comparisons:
        if c["comparable"]:
            detail = ", ".join(
                f"{k} {v:+.1f}%" for k, v in sorted(c["delta_pct"].items())
            ) or "no shared metrics"
            lines.append(f"  r{c['from']:02d} -> r{c['to']:02d}: {detail}")
        else:
            lines.append(
                f"  r{c['from']:02d} -> r{c['to']:02d}: {c['refusal']}"
            )
    return "\n".join(lines)


def diff_rounds(rows: list, a: str, b: str):
    """Pairwise gated diff. Returns (exit_code, text)."""

    def find(token: str):
        token = token.lstrip("r")
        try:
            n = int(token)
        except ValueError:
            return None
        for row in rows:
            if row["round"] == n:
                return row
        return None

    ra, rb = find(a), find(b)
    if ra is None or rb is None:
        return 2, f"unknown round(s): {a!r}, {b!r}"
    if not (ra["parsed"] and rb["parsed"]):
        return 2, "one of the rounds is unparseable"
    if ra["marker"] != rb["marker"]:
        return 2, (
            f"REFUSED: r{ra['round']:02d} and r{rb['round']:02d} were "
            f"measured in different regimes —\n"
            f"  r{ra['round']:02d}: {ra['marker']} [{ra['source']}]\n"
            f"  r{rb['round']:02d}: {rb['marker']} [{rb['source']}]\n"
            f"a cross-regime percentage would be a hardware comparison "
            f"wearing a perf-trajectory costume"
        )
    lines = [
        f"r{ra['round']:02d} -> r{rb['round']:02d} ({ra['marker']}):"
    ]
    for k, va in sorted(ra["metrics"].items()):
        vb = rb["metrics"].get(k)
        if isinstance(vb, (int, float)) and va:
            lines.append(
                f"  {k}: {va} -> {vb} ({(vb - va) / va * 100.0:+.1f}%)"
            )
    if len(lines) == 1:
        lines.append("  no shared metrics")
    return 0, "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--repo", default=REPO)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--diff", nargs=2, metavar=("A", "B"))
    args = ap.parse_args(argv)
    rows = build_rows(args.repo)
    if not rows:
        print("no BENCH_r*.json artifacts found", file=sys.stderr)
        return 1
    if args.diff:
        code, text = diff_rounds(rows, *args.diff)
        print(text)
        return code
    comparisons = trajectory(rows)
    if args.json:
        print(json.dumps({"rounds": rows, "trajectory": comparisons}))
    else:
        print(render(rows, comparisons))
    return 0


if __name__ == "__main__":
    sys.exit(main())
