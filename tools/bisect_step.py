"""Bisect the slab step: time cumulative prefixes of the device program.

The r4 microbench (tools/microbench_gather.py) showed every data-movement
primitive of the step costs <0.4ms at batch 2^20 on the chip, yet the full
step measures ~294ms (tools/profile_engine.py). Some specific composition is
pathological; this times a chain of cumulative prefixes of the exact shipped
program to find the first one that explodes. Each prefix returns reductions
over everything it computed so XLA cannot dead-code-eliminate a stage while
output-write costs stay negligible.

Usage: python tools/bisect_step.py [--batch 1048576] [--slots 8388608]
Prints one JSON object: prefix -> ms/call.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=1 << 20)
    ap.add_argument("--slots", type=int, default=1 << 23)
    ap.add_argument("--keys", type=int, default=10_000_000)
    ap.add_argument("--repeats", type=int, default=8)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from api_ratelimit_tpu.ops.slab import (
        COL_COUNT,
        COL_EXPIRE,
        COL_FP_HI,
        COL_FP_LO,
        COL_WINDOW,
        SlabBatch,
        _sort_key,
    )

    device = jax.devices()[0]
    if device.platform != "tpu" and args.batch > (1 << 14):
        args.batch, args.slots, args.keys = 1 << 13, 1 << 18, 100_000

    b, n = args.batch, args.slots
    rng = np.random.RandomState(0)
    ids_np = (rng.zipf(1.1, size=b).astype(np.uint64) % args.keys).astype(np.uint32)
    ids = jax.device_put(ids_np, device)
    table = jax.device_put(np.zeros((n, 8), np.uint32), device)
    now_i = jnp.int32(1_700_000_000)

    def fmix(x):
        x = x ^ (x >> 16)
        x = x * jnp.uint32(0x85EBCA6B)
        x = x ^ (x >> 13)
        x = x * jnp.uint32(0xC2B2AE35)
        return x ^ (x >> 16)

    def expand(ids):
        return SlabBatch(
            fp_lo=fmix(ids),
            fp_hi=fmix(ids ^ jnp.uint32(0x9E3779B9)),
            hits=jnp.ones_like(ids),
            limit=jnp.full_like(ids, 100),
            divider=jnp.full_like(ids, 1).astype(jnp.int32),
            jitter=jnp.zeros_like(ids).astype(jnp.int32),
        )

    def prefix(stop: str):
        """Build a jitted fn computing the step up to `stop`, returning
        cheap reductions of every live intermediate."""

        def fn(table, ids):
            outs = []
            batch = expand(ids)
            outs.append(batch.fp_lo.sum())
            if stop == "expand":
                return outs
            mask = jnp.uint32(n - 1)
            step = batch.fp_hi | jnp.uint32(1)
            j = jnp.arange(4, dtype=jnp.uint32)
            cand = ((batch.fp_lo[:, None] + j[None, :] * step[:, None]) & mask).astype(
                jnp.int32
            )
            outs.append(cand.sum())
            if stop == "cand":
                return outs
            rows = table[cand]
            outs.append(rows.sum())
            if stop == "gather":
                return outs
            live = rows[:, :, COL_EXPIRE].astype(jnp.int32) > now_i
            match = (
                live
                & (rows[:, :, COL_FP_LO] == batch.fp_lo[:, None])
                & (rows[:, :, COL_FP_HI] == batch.fp_hi[:, None])
            )
            avail = ~live
            match_any = match.any(axis=1)
            avail_any = avail.any(axis=1)
            pick = jnp.where(
                match_any,
                jnp.argmax(match, axis=1),
                jnp.where(avail_any, jnp.argmax(avail, axis=1), 0),
            )
            chosen = jnp.take_along_axis(cand, pick[:, None], axis=1)[:, 0]
            outs.append(chosen.sum())
            if stop == "choose":
                return outs
            picked_rows = jnp.take_along_axis(rows, pick[:, None, None], axis=1)[:, 0]
            outs.append(picked_rows.sum())
            if stop == "pickrows":
                return outs
            key = _sort_key(chosen, batch.fp_hi, n)
            (_, order) = jax.lax.sort(
                (key, jnp.arange(b, dtype=jnp.int32)), num_keys=1, is_stable=True
            )
            outs.append(order.sum())
            if stop == "sort":
                return outs
            s_slot = chosen[order]
            s_fp_lo = batch.fp_lo[order]
            s_fp_hi = batch.fp_hi[order]
            s_hits = batch.hits[order]
            st_rows = picked_rows[order]
            outs.append(s_slot.sum() + s_fp_lo.sum() + st_rows.sum() + s_hits.sum())
            if stop == "permute":
                return outs
            same_prev = (
                (s_slot[1:] == s_slot[:-1])
                & (s_fp_lo[1:] == s_fp_lo[:-1])
                & (s_fp_hi[1:] == s_fp_hi[:-1])
            )
            seg_start = jnp.concatenate([jnp.array([True]), ~same_prev])
            incl = jnp.cumsum(s_hits, dtype=jnp.uint32)
            excl = incl - s_hits
            seg_base_excl = jax.lax.cummax(jnp.where(seg_start, excl, jnp.uint32(0)))
            prior = excl - seg_base_excl
            st_count = st_rows[:, COL_COUNT]
            st_window = st_rows[:, COL_WINDOW].astype(jnp.int32)
            st_expire = st_rows[:, COL_EXPIRE].astype(jnp.int32)
            fp_match = (
                (st_expire > now_i)
                & (st_rows[:, COL_FP_LO] == s_fp_lo)
                & (st_rows[:, COL_FP_HI] == s_fp_hi)
            )
            base = jnp.where(
                (s_hits > 0) & fp_match & (st_window == now_i), st_count, jnp.uint32(0)
            )
            s_after = base + prior + s_hits
            outs.append(s_after.sum())
            if stop == "update":
                return outs
            is_last = jnp.concatenate([s_slot[1:] != s_slot[:-1], jnp.array([True])])
            write_idx = jnp.where(is_last, s_slot, jnp.int32(n))
            new_rows = jnp.stack([s_fp_lo, s_fp_hi, s_after] + [s_fp_lo] * 5, axis=1)
            t2 = table.at[write_idx].set(new_rows, mode="drop", unique_indices=True)
            outs.append(t2[0].sum())
            if stop == "scatter":
                return outs
            unsorted = jnp.zeros_like(s_after).at[order].set(
                s_after, unique_indices=True
            )
            outs.append(unsorted.sum())
            return outs

        return jax.jit(fn)

    def timeit(fn):
        out = fn(table, ids)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(args.repeats):
            out = fn(table, ids)
        jax.block_until_ready(out)
        return round((time.perf_counter() - t0) / args.repeats * 1e3, 3)

    results: dict = {"platform": device.platform, "batch": b, "n_slots": n}
    for stop in (
        "expand",
        "cand",
        "gather",
        "choose",
        "pickrows",
        "sort",
        "permute",
        "update",
        "scatter",
        "unsort",
    ):
        results[stop + "_ms"] = timeit(prefix(stop))
        print(f"[bisect] {stop}: {results[stop + '_ms']}ms", file=sys.stderr)

    print(json.dumps(results))


if __name__ == "__main__":
    main()
