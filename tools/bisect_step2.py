"""Trustworthy bisect of the slab step: the bench's own methodology.

Earlier microbenches gave contradictory numbers on the axon relay —
closure-captured device scalars inflate a program by ~8ms+, and repeated
identical inputs may dedupe server-side. This bisect reproduces the EXACT
conditions of the real bench loop (the one methodology with a corroborated
artifact, BENCH_r03): donated state chained call-to-call, a distinct staged
ids array per call, every scalar a traced literal, block_until_ready on the
state chain. Each prefix of the step is timed that way, so consecutive
prefixes attribute cost to the op they add.
"""

from __future__ import annotations

import argparse
import functools
import json
import sys
import time

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=1 << 20)
    ap.add_argument("--slots", type=int, default=1 << 23)
    ap.add_argument("--keys", type=int, default=10_000_000)
    ap.add_argument("--repeats", type=int, default=8)
    ap.add_argument("--pallas", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from api_ratelimit_tpu.ops.slab import (
        COL_COUNT,
        COL_EXPIRE,
        COL_FP_HI,
        COL_FP_LO,
        COL_WINDOW,
        SlabBatch,
        _sort_key,
    )

    device = jax.devices()[0]
    if device.platform != "tpu" and args.batch > (1 << 14):
        args.batch, args.slots, args.keys = 1 << 13, 1 << 18, 100_000

    b, n = args.batch, args.slots
    R = args.repeats
    rng = np.random.RandomState(0)
    ids_all = (
        rng.zipf(1.1, size=b * R).astype(np.uint64) % args.keys
    ).astype(np.uint32).reshape(R, b)
    staged = [jax.device_put(ids_all[i], device) for i in range(R)]
    for s in staged:
        s.block_until_ready()
    NOW = 1_700_000_000  # python literal -> traced constant

    def fmix(x):
        x = x ^ (x >> 16)
        x = x * jnp.uint32(0x85EBCA6B)
        x = x ^ (x >> 13)
        x = x * jnp.uint32(0xC2B2AE35)
        return x ^ (x >> 16)

    def expand(ids):
        return SlabBatch(
            fp_lo=fmix(ids),
            fp_hi=fmix(ids ^ jnp.uint32(0x9E3779B9)),
            hits=jnp.ones_like(ids),
            limit=jnp.full_like(ids, 100),
            divider=jnp.full_like(ids, 1).astype(jnp.int32),
            jitter=jnp.zeros_like(ids).astype(jnp.int32),
        )

    def build(stop: str):
        """A state-chained step computing the slab program up to `stop`.
        Always returns (new_table, small_out) so the chain and timing match
        the real bench loop exactly. Stages not reached pass the table
        through untouched."""

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(table, ids):
            now = jnp.int32(NOW)
            batch = expand(ids)
            small = batch.fp_lo.sum()
            if stop == "expand":
                return table, small
            mask = jnp.uint32(n - 1)
            pstep = batch.fp_hi | jnp.uint32(1)
            j = jnp.arange(4, dtype=jnp.uint32)
            cand = (
                (batch.fp_lo[:, None] + j[None, :] * pstep[:, None]) & mask
            ).astype(jnp.int32)
            if stop == "cand":
                return table, small + cand.sum()
            rows = table[cand]
            if stop == "gather":
                return table, small + rows.sum()
            live = rows[:, :, COL_EXPIRE].astype(jnp.int32) > now
            match = (
                live
                & (rows[:, :, COL_FP_LO] == batch.fp_lo[:, None])
                & (rows[:, :, COL_FP_HI] == batch.fp_hi[:, None])
            )
            avail = ~live
            match_any = match.any(axis=1)
            avail_any = avail.any(axis=1)
            pick = jnp.where(
                match_any,
                jnp.argmax(match, axis=1),
                jnp.where(avail_any, jnp.argmax(avail, axis=1), 0),
            )
            chosen = jnp.take_along_axis(cand, pick[:, None], axis=1)[:, 0]
            if stop == "choose":
                return table, small + chosen.sum()
            picked_rows = jnp.take_along_axis(rows, pick[:, None, None], axis=1)[
                :, 0
            ]
            if stop == "pickrows":
                return table, small + picked_rows.sum()
            key = _sort_key(chosen, batch.fp_hi, n)
            (_, order) = jax.lax.sort(
                (key, jnp.arange(b, dtype=jnp.int32)), num_keys=1, is_stable=True
            )
            if stop == "sort":
                return table, small + order.sum()
            s_slot = chosen[order]
            s_fp_lo = batch.fp_lo[order]
            s_fp_hi = batch.fp_hi[order]
            s_hits = batch.hits[order]
            st_rows = picked_rows[order]
            if stop == "permute":
                return table, small + s_slot.sum() + st_rows.sum() + s_hits.sum()
            same_prev = (
                (s_slot[1:] == s_slot[:-1])
                & (s_fp_lo[1:] == s_fp_lo[:-1])
                & (s_fp_hi[1:] == s_fp_hi[:-1])
            )
            seg_start = jnp.concatenate([jnp.array([True]), ~same_prev])
            incl = jnp.cumsum(s_hits, dtype=jnp.uint32)
            excl = incl - s_hits
            seg_base = jax.lax.cummax(jnp.where(seg_start, excl, jnp.uint32(0)))
            prior = excl - seg_base
            st_count = st_rows[:, COL_COUNT]
            st_window = st_rows[:, COL_WINDOW].astype(jnp.int32)
            st_expire = st_rows[:, COL_EXPIRE].astype(jnp.int32)
            fp_match = (
                (st_expire > now)
                & (st_rows[:, COL_FP_LO] == s_fp_lo)
                & (st_rows[:, COL_FP_HI] == s_fp_hi)
            )
            base = jnp.where(
                (s_hits > 0) & fp_match & (st_window == now), st_count, jnp.uint32(0)
            )
            s_after = base + prior + s_hits
            if stop == "update":
                return table, small + s_after.sum()
            is_last = jnp.concatenate(
                [s_slot[1:] != s_slot[:-1], jnp.array([True])]
            )
            write_idx = jnp.where(is_last, s_slot, jnp.int32(n))
            new_rows = jnp.stack(
                [s_fp_lo, s_fp_hi, s_after] + [s_fp_lo] * 5, axis=1
            )
            table = table.at[write_idx].set(
                new_rows, mode="drop", unique_indices=True
            )
            if stop == "scatter":
                return table, small + s_after.sum()
            unsorted = jnp.zeros_like(s_after).at[order].set(
                s_after, unique_indices=True
            )
            return table, small + unsorted.sum()

        return step

    def timeit(stop: str) -> float:
        step = build(stop)
        table = jax.device_put(np.zeros((n, 8), np.uint32), device)
        table, out = step(table, staged[-1])  # compile
        jax.block_until_ready((table, out))
        t0 = time.perf_counter()
        outs = []
        for i in range(R):
            table, out = step(table, staged[i])
            outs.append(out)
        jax.block_until_ready(table)
        jax.block_until_ready(outs)
        return round((time.perf_counter() - t0) / R * 1e3, 3)

    results: dict = {"platform": device.platform, "batch": b, "n_slots": n}
    for stop in (
        "expand",
        "cand",
        "gather",
        "choose",
        "pickrows",
        "sort",
        "permute",
        "update",
        "scatter",
        "unsort",
    ):
        results[stop + "_ms"] = timeit(stop)
        print(f"[bisect2] {stop}: {results[stop + '_ms']}ms", file=sys.stderr)

    print(json.dumps(results))


if __name__ == "__main__":
    main()
