#!/usr/bin/env python3
"""Chaos campaign driver (chaos/): seeded nemesis sweeps, replay, shrink.

Sweep (the default; writes a provenance-stamped CHAOS_rNN.json):

    python tools/chaos_campaign.py --seeds 10 --steps 120 --out CHAOS_r19.json

Replay one seed and prove byte-identical determinism:

    python tools/chaos_campaign.py --seed 4 --replay

Self-test the checker: weaken one bound term, catch the violation the
full bound excuses, ddmin it to a minimal repro, emit a pytest file:

    python tools/chaos_campaign.py --seed 3 --weaken crash --shrink \\
        --repro /tmp/chaos_repro.py

Exit status: 0 clean, 1 violations found (or replay mismatch), 2 usage.
The artifact must pass `python tools/bench_lint.py CHAOS_rNN.json` —
the lint demands verified provenance, per-class coverage (or an
explicit skip reason), and the full violation reports inline.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from chaos.campaign import (  # noqa: E402
    CampaignConfig,
    build_artifact,
    run_campaign,
    run_seeds,
)
from chaos.nemesis import (  # noqa: E402
    NEMESIS_CLASSES,
    canonical_json,
    draw_timeline,
)
from chaos.shrink import emit_repro, shrink_timeline  # noqa: E402


def _config(args) -> CampaignConfig:
    kw = {}
    if args.steps is not None:
        kw["steps"] = args.steps
    if args.classes:
        kw["classes"] = tuple(args.classes.split(","))
    if args.rate is not None:
        kw["nemesis_rate"] = args.rate
    if args.weaken:
        # the weaken self-test isolates the named term: kills only, one
        # over-offered key, no eviction/federation slack masking it
        kw.setdefault("classes", ("process_kill",))
        kw.setdefault("tracked_keys", 1)
        kw.setdefault("lease_offers", 8)
        kw["fillers"] = kw["fillers_per_step"] = 0
        kw["fed_offers"] = 0
        kw["snapshot_every"] = kw["victim_every"] = 0
        kw.setdefault("steps", 40)
    return CampaignConfig(**kw)


def _cmd_replay(args, config: CampaignConfig) -> int:
    first = run_campaign(args.seed, config=config)
    second = run_campaign(args.seed, config=config)
    same = canonical_json(first) == canonical_json(second)
    print(
        f"seed {args.seed}: timeline_crc={first['timeline_crc']} "
        f"verdict={first['verdict']} "
        f"replay={'byte-identical' if same else 'MISMATCH'}"
    )
    return 0 if same and first["verdict"] == "ok" else 1


def _cmd_shrink(args, config: CampaignConfig) -> int:
    timeline = draw_timeline(
        args.seed, config.steps, config.classes, config.nemesis_rate
    )
    result = run_campaign(
        args.seed, config=config, timeline=timeline, weaken=args.weaken
    )
    if result["verdict"] != "violation":
        print(
            f"seed {args.seed}: no violation even with {args.weaken!r} "
            f"weakened ({len(timeline)} actions) — try another seed"
        )
        return 1
    print(
        f"seed {args.seed}: weakened {args.weaken!r} violated "
        f"({len(timeline)} actions); shrinking..."
    )
    minimal = shrink_timeline(
        args.seed, timeline, config=config, weaken=args.weaken
    )
    print(f"minimal repro: {len(minimal)} action(s)")
    for action in minimal:
        print(f"  {canonical_json(action)}")
    if args.repro:
        emit_repro(
            args.repro, args.seed, minimal, config=config, weaken=args.weaken
        )
        print(f"pytest repro written: {args.repro}")
    return 0


def _cmd_sweep(args, config: CampaignConfig) -> int:
    seeds = list(range(args.seeds))

    def progress(result):
        cov = {k: v for k, v in result["coverage"].items() if v}
        print(
            f"seed {result['seed']}: {result['verdict']} "
            f"crc={result['timeline_crc']} "
            f"admits={sum(result['ledger']['admits'].values())} "
            f"denies={result['ledger']['denies']} cov={cov}"
        )

    results = run_seeds(
        seeds, config=config, weaken=args.weaken or None, progress=progress
    )
    artifact = build_artifact(results, config, args.round)
    if args.out:
        # one JSON line, sorted keys: the same canonical shape every
        # BENCH artifact uses (tools/bench_lint.py parses the last line)
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(artifact, f, sort_keys=True, separators=(",", ":"))
            f.write("\n")
        print(f"artifact written: {args.out}")
    n_viol = len(artifact["violations"])
    print(f"verdict: {artifact['verdict']} ({n_viol} violation(s))")
    for violation in artifact["violations"]:
        print(f"  {canonical_json(violation)}")
    return 1 if n_viol else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--seeds", type=int, default=10)
    parser.add_argument("--seed", type=int, default=None)
    parser.add_argument("--steps", type=int, default=None)
    parser.add_argument(
        "--classes",
        default="",
        help=f"comma list; default all of {','.join(NEMESIS_CLASSES)}",
    )
    parser.add_argument("--rate", type=float, default=None)
    parser.add_argument("--out", default="")
    parser.add_argument("--round", type=int, default=19)
    parser.add_argument("--replay", action="store_true")
    parser.add_argument("--weaken", default="")
    parser.add_argument("--shrink", action="store_true")
    parser.add_argument("--repro", default="")
    args = parser.parse_args(argv)

    import logging

    logging.disable(logging.CRITICAL)  # nemesis noise is the point
    config = _config(args)
    if args.replay:
        if args.seed is None:
            parser.error("--replay needs --seed")
        return _cmd_replay(args, config)
    if args.shrink:
        if args.seed is None or not args.weaken:
            parser.error("--shrink needs --seed and --weaken")
        return _cmd_shrink(args, config)
    return _cmd_sweep(args, config)


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())
