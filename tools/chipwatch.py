"""Unattended on-chip measurement chain for flaky chip windows.

The round-5 tunnel pattern: down for 18+ hours, then a window opens that
is long enough for small-batch probes (divtest completed: add 0.026ms /
float_div 0.029ms / recip_div 0.027ms at 2^20 — division exonerated) but
dies during the first 256MB slab staging of engine_ab2. This driver
makes every future window count without a human in the loop:

  probe -> linkprobe -> divtest -> engine_ab2(small slab) ->
  engine_ab2(full) -> Pallas TPU tests -> bench.py

Per-stage subprocess timeouts; after any stage failure the device is
re-probed (a wedged tunnel fails the probe and we go back to waiting)
and completed stages are never re-run. All output streams into the log
with flushed per-stage headers so a dead window still yields evidence.

Usage:  nohup python -m tools.chipwatch > /tmp/chipwatch.log 2>&1 &
        (add --resume to continue a prior chain after a watcher crash;
        the default start re-measures everything)
State:  /tmp/chipwatch_state.json (stage completion), logs under /tmp,
        bench artifact copied to BENCH_r05_chip_try.json on success.
A stage only counts as done when its output proves it ran on the chip
(platform marker / tests actually passed) — rc==0 on the CPU fallback
is a failed window, not evidence.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# the staged-run machinery (descendant-tree kill, offset-scoped marker
# search, ok/fail/timeout/fallback classification) generalized into the
# bench-driver subsystem; chipwatch keeps its chain semantics and env
# overrides on top of it
from tools import bench_driver as _driver
STATE_PATH = "/tmp/chipwatch_state.json"
# The probe must resolve the platform EXACTLY like the stages do
# (respect_jax_platforms_env, then ask jax) and compare the last line
# whole — substring-matching all of stdout would pass on a plugin banner
# mentioning "tpu", and skipping the env re-assert would let the probe
# see the chip while every stage pins itself to cpu.
PROBE_CMD = [
    sys.executable,
    "-c",
    "from api_ratelimit_tpu.utils.jaxsetup import respect_jax_platforms_env;"
    "respect_jax_platforms_env();"
    "import jax; print(jax.devices()[0].platform)",
]

# (name, argv, timeout_s, success_marker). Order is cheapest-first so a
# short window still produces the highest-information-per-second
# evidence. success_marker must appear in the output THIS run appended —
# rc==0 alone is not success: if the window dies between our probe and
# the stage's jax init, the tools downscale onto the CPU fallback and
# exit 0, and the pallas test module skips itself cleanly.
TPU_MARK = '"platform": "tpu"'
STAGES = [
    ("linkprobe", [sys.executable, "-m", "tools.linkprobe"], 900, TPU_MARK),
    ("divtest", [sys.executable, "-m", "tools.divtest"], 900, TPU_MARK),
    (
        "ab2_small",
        [sys.executable, "-m", "tools.engine_ab2", "--slots", str(1 << 21)],
        1800,
        TPU_MARK,
    ),
    # Batch ladder point: residual linear in batch => transfer-bound
    # (tunnel bandwidth); constant => per-launch overhead. One extra
    # geometry answers it with the same tool.
    (
        "ab2_batch64k",
        [
            sys.executable,
            "-m",
            "tools.engine_ab2",
            "--batch",
            str(1 << 16),
            "--slots",
            str(1 << 21),
        ],
        1800,
        TPU_MARK,
    ),
    ("ab2_full", [sys.executable, "-m", "tools.engine_ab2"], 2400, TPU_MARK),
    (
        "pallas_tests",
        [sys.executable, "-m", "pytest", "tests/test_pallas_tpu.py", "-q"],
        1800,
        " passed",
    ),
    # Timeout must exceed bench's own forced-emit horizon (BENCH_BUDGET_S
    # 780 + 120s watchdog + jax-init slack) or we SIGKILL the tree before
    # the watchdog can land the artifact line.
    ("bench", [sys.executable, "bench.py"], 1200, TPU_MARK),
]


def log(msg: str) -> None:
    print(f"[chipwatch {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def load_state() -> dict:
    try:
        with open(STATE_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {"done": []}


def save_state(state: dict) -> None:
    with open(STATE_PATH, "w") as f:
        json.dump(state, f)


def probe(timeout_s: float = 90.0) -> bool:
    """90s covers the observed healthy-tunnel init (~30-60s) while keeping
    worst-case window-detection latency ~2 minutes — window #1 lasted only
    ~25 minutes, so detection latency is chain time stolen."""
    try:
        out = subprocess.run(
            PROBE_CMD,
            cwd=REPO,
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
        lines = [ln.strip() for ln in out.stdout.splitlines() if ln.strip()]
        ok = out.returncode == 0 and bool(lines) and lines[-1] == "tpu"
        if not ok:
            log(f"probe rc={out.returncode} out={out.stdout.strip()!r}")
        return ok
    except subprocess.TimeoutExpired:
        log(f"probe timeout after {timeout_s:.0f}s")
        return False


def _descendants(root: int) -> list:
    """/proc PPID-walk descendant listing (tools/bench_driver.py)."""
    return _driver.descendants(root)


def _kill_tree(pid: int) -> None:
    """Snapshot-then-kill of the whole descendant tree (bench_driver)."""
    _driver.kill_tree(pid)


def run_stage(name: str, argv: list, timeout_s: float, marker: str) -> str:
    """Returns "ok" | "fail" | "timeout" | "fallback" (rc==0, no marker).

    The execution machinery lives in tools/bench_driver.run_stage;
    chipwatch adds the chain's env overrides on top."""
    env = dict(os.environ)
    if name == "pallas_tests":
        env["TPU_TESTS"] = "1"
    if name == "bench":
        # Forced mode: no silent CPU fallback — a dead window makes the
        # stage fail (and not count, per the probe-gated failure rule)
        # instead of recording a CPU artifact as chip evidence. The
        # budget is raised above the driver's default so this one chip
        # run can complete every tier (the stage timeout still bounds
        # it); slow-compile time is the usual cost, not measurement.
        env["BENCH_PLATFORM"] = "tpu"
        env.setdefault("BENCH_BUDGET_S", "780")
    return _driver.run_stage(
        name,
        argv,
        timeout_s,
        marker,
        env=env,
        log_path=f"/tmp/chip_{name}.log",
        log_prefix="chipwatch",
    )


MAX_STAGE_FAILURES = 3


def harvest(state: dict) -> None:
    """Copy evidence into the repo: the bench JSON line — only if THIS
    chain's bench stage succeeded (/tmp/chip_bench.log is append-only
    across chains; republishing its last line unconditionally would
    present a stale pre-relaunch artifact as this chain's evidence) —
    and the chain's own log, always."""
    if "bench" in state["done"]:
        try:
            with open("/tmp/chip_bench.log", "rb") as f:
                lines = [
                    ln
                    for ln in f.read().decode(errors="replace").splitlines()
                    if ln.startswith('{"metric"')
                ]
            if lines:
                with open(os.path.join(REPO, "BENCH_r05_chip_try.json"), "w") as f:
                    f.write(lines[-1] + "\n")
        except OSError:
            pass
    try:
        subprocess.run(["cp", "/tmp/chipwatch.log", os.path.join(REPO, "CHIP_RUN_r5.log")])
    except OSError:
        pass


def main() -> None:
    # Fresh by default: the state file is for resuming THIS chain after a
    # watcher crash (--resume), not for surviving intentional relaunches —
    # a relaunch after a code fix or for a new round must re-measure, not
    # silently skip stages a stale file marked done.
    if "--resume" in sys.argv[1:]:
        state = load_state()
        log(f"resuming: done={state['done']}")
    else:
        state = {"done": []}
        save_state(state)
    failures: dict = {}
    attempt = 0
    while True:
        # Repeatedly-failing stages are DEMOTED to the end of the pass,
        # not dropped: a slow-but-alive tunnel can time a heavy stage
        # out with the tiny probe still passing, and permanent exclusion
        # would then skip the chain's primary measurement in a later
        # healthy window. The chain only finishes early if EVERY
        # remaining stage has hit the failure cap.
        remaining = sorted(
            (s for s in STAGES if s[0] not in state["done"]),
            key=lambda s: (
                failures.get(s[0], 0) >= MAX_STAGE_FAILURES,
                STAGES.index(s),
            ),
        )
        if not remaining or all(
            failures.get(s[0], 0) >= MAX_STAGE_FAILURES for s in remaining
        ):
            log(f"chain finished: done={state['done']} failures={failures}")
            harvest(state)
            return
        attempt += 1
        if not probe():
            time.sleep(45)
            continue
        log(f"window open (attempt {attempt}); {len(remaining)} stages remain")
        for name, argv, timeout_s, marker in remaining:
            outcome = run_stage(name, argv, timeout_s, marker)
            if outcome == "ok":
                state["done"].append(name)
                save_state(state)
                continue
            # Re-probe to distinguish "tunnel died" (wait for a new
            # window; nothing counted) from a live device. Only a
            # nonzero exit with the device alive counts as a
            # deterministic stage failure — timeouts and silent CPU
            # fallbacks are window symptoms even when the probe passes.
            alive = probe()
            counted = alive and outcome == "fail"
            if counted:
                failures[name] = failures.get(name, 0) + 1
            log(
                f"stage {name} {outcome} (counted={counted}, "
                f"count={failures.get(name, 0)}); device alive={alive}"
            )
            if not alive:
                break
        harvest(state)
        time.sleep(30)


if __name__ == "__main__":
    main()
