#!/usr/bin/env python3
"""Clock-injection lint: no raw wall/monotonic reads in time-semantic code.

The chaos engine's determinism contract (chaos/) requires every
time-SEMANTIC read — window math, TTLs, lease expiry, breaker windows,
settlement lag, snapshot staleness — to route through an injectable
TimeSource (utils/timeutil.py), so a campaign can virtualize and skew
one process's clock. This lint walks the module list below and flags:

    time.time(...)        always time-semantic — use ts.unix_now()
    time.monotonic(...)   interval semantics — use ts.monotonic()

Exempt by construction (pure measurement, never decision input):

    time.perf_counter / perf_counter_ns   latency histograms
    time.monotonic_ns                     journey stage stamps
    time.sleep                            pacing, not reading

A line that must read the real clock (the RealTimeSource itself, the
process-bootstrap path) carries a `# clock-ok: <reason>` pragma.

Exit 0 clean, 1 findings, 2 usage. Wired into tier-1 via
tests/test_chaos_engine.py so a raw clock read can't land unseen.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = "api_ratelimit_tpu"

# The time-SEMANTIC module list: files whose clock reads feed decisions
# (windows, TTLs, expiry, lag, staleness). Measurement-only modules
# (tracing, stats, bench tools) are out of scope by design.
SEMANTIC_MODULES = (
    "backends/tpu.py",
    "backends/lease.py",
    "backends/sidecar.py",
    "backends/fallback.py",
    "backends/victim.py",
    "backends/memory.py",
    "backends/overload.py",
    "limiter/base_limiter.py",
    "limiter/local_cache.py",
    "cluster/federation.py",
    "persist/replication.py",
    "persist/snapshot.py",
    "persist/snapshotter.py",
    "parallel/sharded_slab.py",
    "service/ratelimit.py",
    "utils/timeutil.py",
)

_RAW = re.compile(r"\btime\.(time|monotonic)\(")
_EXEMPT = re.compile(r"\btime\.(perf_counter|perf_counter_ns|monotonic_ns|sleep)\b")
_PRAGMA = "# clock-ok"


def lint_file(path: str) -> list:
    findings = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            lines = f.readlines()
    except OSError as exc:
        return [f"{path}: unreadable ({exc})"]
    for lineno, line in enumerate(lines, 1):
        stripped = line.split("#", 1)[0]
        match = _RAW.search(stripped)
        if match is None:
            continue
        if _PRAGMA in line:
            continue
        findings.append(
            f"{os.path.relpath(path, REPO)}:{lineno}: raw time.{match.group(1)}() "
            f"in a time-semantic module — route through the TimeSource "
            f"(utils/timeutil.py process_time_source) or add "
            f"'# clock-ok: <reason>'"
        )
    return findings


def run(repo: str = REPO) -> list:
    findings = []
    for rel in SEMANTIC_MODULES:
        path = os.path.join(repo, PKG, rel)
        if not os.path.exists(path):
            findings.append(f"{PKG}/{rel}: listed module missing")
            continue
        findings.extend(lint_file(path))
    return findings


def main(argv=None) -> int:
    findings = run()
    for finding in findings:
        print(finding)
    if findings:
        print(f"clock_lint: {len(findings)} finding(s)")
        return 1
    print(f"clock_lint: clean ({len(SEMANTIC_MODULES)} modules)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
