"""Is float32 division itself a slow op-class on this stack?

The idiv -> float-div replacement did not move the real step (~318ms before
and after), yet the division-free bisect runs at 0.1ms — consistent with
f32 division being as pathological as integer division. This times, with
trusted methodology (varied staged inputs, traced literals only):
  * an add pass (control)
  * a floor(a/b) float-division pass
  * the same quotient via a division-free reciprocal: exponent-flip bit
    trick seed + 3 Newton iterations (mul/sub/bitcast only)
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def recip_f32(bf):
    """The SHIPPED reciprocal (ops/decide.py) — imported, not copied, so
    this probe always times and accuracy-checks what the engine runs."""
    from api_ratelimit_tpu.ops.decide import _recip_f32

    return _recip_f32(bf)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=1 << 20)
    ap.add_argument("--repeats", type=int, default=8)
    args = ap.parse_args()

    from api_ratelimit_tpu.utils.jaxsetup import respect_jax_platforms_env

    respect_jax_platforms_env()
    import jax
    import jax.numpy as jnp

    device = jax.devices()[0]
    b = args.batch
    if device.platform != "tpu" and b > (1 << 14):
        b = 1 << 13

    rng = np.random.RandomState(0)
    xs = [
        jax.device_put(rng.randint(1, 1 << 27, size=b).astype(np.int32), device)
        for _ in range(args.repeats)
    ]
    results: dict = {"platform": device.platform, "batch": b}

    def timeit(label, f):
        g = jax.jit(f)
        out = g(xs[-1])
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        outs = [g(x) for x in xs]
        jax.block_until_ready(outs)
        results[label] = round((time.perf_counter() - t0) / len(xs) * 1e3, 3)
        print(f"[divtest] {label}: {results[label]}ms", file=sys.stderr)

    timeit("add", lambda x: x + jnp.int32(1))

    def fdiv(x):
        af = x.astype(jnp.float32)
        bf = (x & 1023).astype(jnp.float32) + jnp.float32(1.0)
        return jnp.floor(af / bf).astype(jnp.int32)

    timeit("float_div", fdiv)

    def rdiv(x):
        af = x.astype(jnp.float32)
        bf = (x & 1023).astype(jnp.float32) + jnp.float32(1.0)
        return jnp.floor(af * recip_f32(bf)).astype(jnp.int32)

    timeit("recip_div", rdiv)

    # correctness cross-check of the RAW quotient band: recip-multiply vs
    # true, worst deviation over a batch. This is the FIRST-ESTIMATE band,
    # dominated by the int->f32 rounding of a (exact only below 2^24): CPU
    # measures ~8 at a~2^27, and that is fine — the SHIPPED
    # floor_div_exact_i32 refines the estimate with an integer residual
    # pass plus a +-1 fixup and is pinned exact by tests/test_decide. On
    # chip, compare against the CPU figure: same order => same seed/refine
    # budget suffices; orders larger => the chip's f32 multiply/rounding
    # differs and the exact path needs re-validation there.
    x = np.asarray(xs[0])
    a = x.astype(np.int64)
    d = (x & 1023).astype(np.int64) + 1
    got = np.asarray(jax.jit(rdiv)(xs[0])).astype(np.int64)
    dev = np.abs(got - a // d).max()
    results["recip_max_quotient_dev"] = int(dev)

    print(json.dumps(results))


if __name__ == "__main__":
    main()
