"""A/B the real engine step programs on the attached device: XLA vs Pallas.

tools/bisect_step2.py (bench methodology: chained donated state, varied
staged inputs, literal scalars) showed the all-XLA slab program completes in
~0.1-0.2ms at batch 2^20 — while BENCH_r03's pallas=True headline ran at
261ms/step. This times the REAL shipped step functions end to end (decide +
packbits + health + readback), both engines, so the bench's default engine
choice is driven by a recorded head-to-head.

Usage: python tools/engine_ab.py [--batch 1048576] [--slots 8388608]
"""

from __future__ import annotations

import argparse
import functools
import json
import sys
import time

import numpy as np

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _ab_common import NOW_LIT, downscale, make_expand, stage_zipf_ids


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=1 << 20)
    ap.add_argument("--slots", type=int, default=1 << 23)
    ap.add_argument("--keys", type=int, default=10_000_000)
    ap.add_argument("--repeats", type=int, default=8)
    ap.add_argument("--skip-pallas", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from api_ratelimit_tpu.ops.slab import (
        SlabBatch,
        _slab_step_sorted,
        _slab_update_sorted,
        _unsort,
        make_slab,
    )

    device = jax.devices()[0]
    downscale(args, device.platform)
    b, n = args.batch, args.slots
    R = args.repeats
    now_lit = NOW_LIT

    expand = make_expand()

    @functools.partial(
        jax.jit, donate_argnames=("state",), static_argnames=("use_pallas",)
    )
    def bench_step(state, ids, use_pallas):
        state, _b, _a, d, order, health = _slab_step_sorted(
            state,
            expand(ids),
            jnp.int32(now_lit),
            jnp.float32(0.8),
            ways=128,
            use_pallas=use_pallas,
            count_health=True,
            lean_decide=use_pallas,
        )
        over = _unsort(d.code, order) == 2
        return state, jnp.packbits(over), health

    @functools.partial(
        jax.jit, donate_argnames=("state",), static_argnames=("use_pallas",)
    )
    def after_step(state, ids, use_pallas):
        state, _b, s_after, _i, order, health, _ = _slab_update_sorted(
            state,
            expand(ids),
            jnp.int32(now_lit),
            ways=128,
            count_health=True,
            use_pallas=use_pallas,
        )
        after = jnp.minimum(_unsort(s_after, order), jnp.uint32(255))
        return state, after.astype(jnp.uint8), health

    staged = stage_zipf_ids(device, b, args.keys, R + 1)

    results: dict = {"platform": device.platform, "batch": b, "n_slots": n}

    def run(step, label, flag):
        state = jax.device_put(make_slab(n), device)
        state, out, health = step(state, staged[-1], flag)
        np.asarray(out)
        t0 = time.perf_counter()
        outs = []
        for i in range(R):
            state, out, health = step(state, staged[i], flag)
            outs.append(out)
        jax.block_until_ready(state)
        t_device = time.perf_counter() - t0
        fetched = [np.asarray(o) for o in outs]
        t_e2e = time.perf_counter() - t0
        entry = {
            "ms_per_step_device": round(t_device / R * 1e3, 3),
            "ms_per_step_e2e": round(t_e2e / R * 1e3, 3),
            "rate": round(R * b / t_e2e),
        }
        results[label] = entry
        print(f"[ab:{label}] {entry}", file=sys.stderr)
        return fetched

    bits_x = run(bench_step, "decided_xla", False)
    run(after_step, "after_xla", False)
    if device.platform == "tpu" and not args.skip_pallas:
        try:
            bits_p = run(bench_step, "decided_pallas", True)
            results["decided_bits_equal"] = all(
                np.array_equal(a, c) for a, c in zip(bits_x, bits_p)
            )
        except Exception as e:
            results["pallas_error"] = str(e)[-300:]

    print(json.dumps(results))


if __name__ == "__main__":
    main()
