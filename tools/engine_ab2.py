"""Close the bisect gap: real slab functions, incremental output variants.

bisect_step2 (inline ops, scalar output) = 0.11ms/step; engine_ab (real
functions, array outputs) = ~318ms/step — after the division fix. The delta
hides in what the bisect skipped: the real update's row stack, health
reductions, decide(), _unsort, the u8 cast, packbits, or ARRAY OUTPUTS
themselves. Each variant here uses the REAL shipped functions, chained
donated state, varied staged inputs, adding one suspect at a time.
"""

from __future__ import annotations

import argparse
import functools
import json
import sys
import time

import numpy as np

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _ab_common import NOW_LIT, downscale, make_expand, stage_zipf_ids


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=1 << 20)
    ap.add_argument("--slots", type=int, default=1 << 23)
    ap.add_argument("--keys", type=int, default=10_000_000)
    ap.add_argument("--repeats", type=int, default=8)
    args = ap.parse_args()

    from api_ratelimit_tpu.utils.jaxsetup import respect_jax_platforms_env

    respect_jax_platforms_env()
    import jax
    import jax.numpy as jnp

    from api_ratelimit_tpu.ops.decide import decide
    from api_ratelimit_tpu.ops.slab import (
        SlabBatch,
        _slab_step_sorted,
        _slab_update_sorted,
        _unsort,
        make_slab,
    )

    device = jax.devices()[0]
    downscale(args, device.platform)
    b, n = args.batch, args.slots
    R = args.repeats
    now_lit = NOW_LIT

    expand = make_expand()

    print(f"[ab2] staging {R + 1} x {b * 4 >> 20}MB id arrays", file=sys.stderr, flush=True)
    staged = stage_zipf_ids(device, b, args.keys, R + 1)
    # Placement check: CPU step time at batch 8192 extrapolates to
    # ~346ms at 2^20 — almost exactly the on-chip ~318ms residual. If a
    # buffer or computation silently lands on the host (axon relay
    # quirk), every "device" measurement here is actually CPU speed;
    # make placement explicit in the log.
    print(f"[ab2] staged[0].devices = {staged[0].devices()}", file=sys.stderr, flush=True)
    print("[ab2] staging done", file=sys.stderr, flush=True)

    results: dict = {"platform": device.platform, "batch": b, "n_slots": n}

    def timed(label, step, raw_table=False):
        print(
            f"[ab2:{label}] staging {n * 32 >> 20}MB slab",
            file=sys.stderr,
            flush=True,
        )
        state = jax.device_put(make_slab(n), device)
        jax.block_until_ready(state)
        print(f"[ab2:{label}] slab staged; warmup compile", file=sys.stderr, flush=True)
        if raw_table:
            state = state.table
        out = step(state, staged[-1])
        state = out[0]
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        outs = []
        for i in range(R):
            out = step(state, staged[i])
            state = out[0]
            outs.append(out[1:])
        jax.block_until_ready(state)
        t_dev = time.perf_counter() - t0
        # device_get, not block_until_ready: the e2e leg must pay the
        # actual D2H readback (the ~280ms/step prime suspect over the
        # ~14MB/s tunnel) or array-out variants would read as free.
        fetched = jax.device_get(outs)
        t_e2e = time.perf_counter() - t0
        leaf = jax.tree_util.tree_leaves(state)[0]
        results[label] = {
            "ms_device": round(t_dev / R * 1e3, 3),
            "ms_e2e": round(t_e2e / R * 1e3, 3),
            "state_devices": str(leaf.devices()),
        }
        print(f"[ab2:{label}] {results[label]}", file=sys.stderr, flush=True)

    # v0: the bisect's fastest inline program through THIS harness —
    # same probe/sort/permute/update/scatter, no floor_div, no decide,
    # no health, scalar out; rules out harness differences in one number
    from api_ratelimit_tpu.ops.slab import _choose_ways, _sort_key

    @functools.partial(jax.jit, donate_argnames=("state",))
    def v0(state, ids):
        import jax.numpy as jnp2

        batch = expand(ids)
        now = jnp.int32(now_lit)
        chosen, _cls, matched, picked_rows = _choose_ways(state, batch, now, 128)
        bsz = chosen.shape[0]
        key = _sort_key(chosen, matched, batch.fp_hi, state.n_slots)
        (_, order) = jax.lax.sort(
            (key, jnp.arange(bsz, dtype=jnp.int32)), num_keys=1, is_stable=True
        )
        s_slot = chosen[order]
        s_fp_lo = batch.fp_lo[order]
        s_fp_hi = batch.fp_hi[order]
        s_hits = batch.hits[order]
        st_rows = picked_rows[order]
        same_prev = (
            (s_slot[1:] == s_slot[:-1])
            & (s_fp_lo[1:] == s_fp_lo[:-1])
            & (s_fp_hi[1:] == s_fp_hi[:-1])
        )
        seg_start = jnp.concatenate([jnp.array([True]), ~same_prev])
        incl = jnp.cumsum(s_hits, dtype=jnp.uint32)
        excl = incl - s_hits
        seg_base = jax.lax.cummax(jnp.where(seg_start, excl, jnp.uint32(0)))
        prior = excl - seg_base
        base = jnp.where(
            (s_hits > 0)
            & (st_rows[:, 4].astype(jnp.int32) > now)
            & (st_rows[:, 0] == s_fp_lo)
            & (st_rows[:, 1] == s_fp_hi),
            st_rows[:, 2],
            jnp.uint32(0),
        )
        s_after = base + prior + s_hits
        is_last = jnp.concatenate([s_slot[1:] != s_slot[:-1], jnp.array([True])])
        write_idx = jnp.where(is_last, s_slot, jnp.int32(state.n_slots))
        new_rows = jnp.stack([s_fp_lo, s_fp_hi, s_after] + [s_fp_lo] * 5, axis=1)
        table = state.table.at[write_idx].set(
            new_rows, mode="drop", unique_indices=True
        )
        from api_ratelimit_tpu.ops.slab import SlabState

        return SlabState(table=table), s_after.sum()

    timed("v0_inline_nodivide", v0)

    # v00: byte-for-byte the bisect_step2 final program — RAW table arg
    # (not SlabState), donate_argnums, scalar out. If v00 is fast and v0
    # slow, the difference is the harness/pytree, not the program.
    @functools.partial(jax.jit, donate_argnums=(0,))
    def v00(table, ids):
        from api_ratelimit_tpu.ops.slab import SlabState

        st = SlabState(table=table)
        batch = expand(ids)
        now = jnp.int32(now_lit)
        chosen, _cls, matched, picked_rows = _choose_ways(st, batch, now, 128)
        bsz = chosen.shape[0]
        key = _sort_key(chosen, matched, batch.fp_hi, n)
        (_, order) = jax.lax.sort(
            (key, jnp.arange(bsz, dtype=jnp.int32)), num_keys=1, is_stable=True
        )
        s_slot = chosen[order]
        s_fp_lo = batch.fp_lo[order]
        s_fp_hi = batch.fp_hi[order]
        s_hits = batch.hits[order]
        st_rows = picked_rows[order]
        seg_start = jnp.concatenate(
            [jnp.array([True]),
             ~((s_slot[1:] == s_slot[:-1])
               & (s_fp_lo[1:] == s_fp_lo[:-1])
               & (s_fp_hi[1:] == s_fp_hi[:-1]))]
        )
        incl = jnp.cumsum(s_hits, dtype=jnp.uint32)
        excl = incl - s_hits
        seg_base = jax.lax.cummax(jnp.where(seg_start, excl, jnp.uint32(0)))
        prior = excl - seg_base
        base = jnp.where(
            (s_hits > 0)
            & (st_rows[:, 4].astype(jnp.int32) > now)
            & (st_rows[:, 0] == s_fp_lo)
            & (st_rows[:, 1] == s_fp_hi),
            st_rows[:, 2],
            jnp.uint32(0),
        )
        s_after = base + prior + s_hits
        is_last = jnp.concatenate([s_slot[1:] != s_slot[:-1], jnp.array([True])])
        write_idx = jnp.where(is_last, s_slot, jnp.int32(n))
        new_rows = jnp.stack([s_fp_lo, s_fp_hi, s_after] + [s_fp_lo] * 5, axis=1)
        table = table.at[write_idx].set(new_rows, mode="drop", unique_indices=True)
        return table, s_after.sum()

    timed("v00_rawtable_bisect", v00, raw_table=True)

    # v1: REAL update (health off), scalar out
    @functools.partial(jax.jit, donate_argnames=("state",))
    def v1(state, ids):
        state, _b, s_after, _i, order, health, _ = _slab_update_sorted(
            state, expand(ids), jnp.int32(now_lit), 4, count_health=False
        )
        return state, s_after.sum()

    timed("update_scalar", v1)

    # v2: + health reductions
    @functools.partial(jax.jit, donate_argnames=("state",))
    def v2(state, ids):
        state, _b, s_after, _i, order, health, _ = _slab_update_sorted(
            state, expand(ids), jnp.int32(now_lit), 4, count_health=True
        )
        return state, s_after.sum() + health.sum()

    timed("update_health_scalar", v2)

    # v3: + unsort + u8 cast, still scalar out
    @functools.partial(jax.jit, donate_argnames=("state",))
    def v3(state, ids):
        state, _b, s_after, _i, order, health, _ = _slab_update_sorted(
            state, expand(ids), jnp.int32(now_lit), 4, count_health=True
        )
        after = jnp.minimum(_unsort(s_after, order), jnp.uint32(255))
        return state, after.astype(jnp.uint8).sum() + health.sum()

    timed("after_scalar", v3)

    # v4: after-mode with the REAL array output (u8[b])
    @functools.partial(jax.jit, donate_argnames=("state",))
    def v4(state, ids):
        state, _b, s_after, _i, order, health, _ = _slab_update_sorted(
            state, expand(ids), jnp.int32(now_lit), 4, count_health=True
        )
        after = jnp.minimum(_unsort(s_after, order), jnp.uint32(255))
        return state, after.astype(jnp.uint8), health

    timed("after_array", v4)

    # v4b: array output WITHOUT the u8 min/cast — splits "returning an
    # array" from "the narrowing cast" if v4 is slow
    @functools.partial(jax.jit, donate_argnames=("state",))
    def v4b(state, ids):
        state, _b, s_after, _i, order, health, _ = _slab_update_sorted(
            state, expand(ids), jnp.int32(now_lit), 4, count_health=True
        )
        return state, _unsort(s_after, order), health

    timed("after_array_u32", v4b)

    # v5: + decide() on sorted results, scalar out
    @functools.partial(jax.jit, donate_argnames=("state",))
    def v5(state, ids):
        state, _b, _a, d, order, health = _slab_step_sorted(
            state,
            expand(ids),
            jnp.int32(now_lit),
            jnp.float32(0.8),
            ways=128,
            use_pallas=False,
            count_health=True,
        )
        return state, d.code.sum() + health.sum()

    timed("decided_scalar", v5)

    # v6: + unsort(code) + ==2 + packbits (the real bench_step output)
    @functools.partial(jax.jit, donate_argnames=("state",))
    def v6(state, ids):
        state, _b, _a, d, order, health = _slab_step_sorted(
            state,
            expand(ids),
            jnp.int32(now_lit),
            jnp.float32(0.8),
            ways=128,
            use_pallas=False,
            count_health=True,
        )
        over = _unsort(d.code, order) == 2
        return state, jnp.packbits(over), health

    timed("decided_packbits", v6)

    # v7: same output bits via the multiply-add packer (ops/decide.py
    # packbits_muladd) — the candidate swap if v6 shows packbits' shift/or
    # lowering is another pathological vector op class (like division was)
    from api_ratelimit_tpu.ops.decide import packbits_muladd

    @functools.partial(jax.jit, donate_argnames=("state",))
    def v7(state, ids):
        state, _b, _a, d, order, health = _slab_step_sorted(
            state,
            expand(ids),
            jnp.int32(now_lit),
            jnp.float32(0.8),
            ways=128,
            use_pallas=False,
            count_health=True,
        )
        over = _unsort(d.code, order) == 2
        return state, packbits_muladd(over), health

    timed("decided_muladd_pack", v7)

    # v8: v6's program with use_pallas=True — the engine BENCH_r03's 4.0M
    # headline actually ran. Every other variant is the XLA twin; if the
    # residual lives in the Mosaic kernel (e.g. the SMEM-carry grid
    # serializing at 2^20/block_rows steps), only this row shows it.
    # TPU only: interpret-mode Pallas on CPU is minutes per step and the
    # CPU smoke run's job is validating the harness, not timing Mosaic.
    if device.platform == "tpu":

        @functools.partial(jax.jit, donate_argnames=("state",))
        def v8(state, ids):
            state, _b, _a, d, order, health = _slab_step_sorted(
                state,
                expand(ids),
                jnp.int32(now_lit),
                jnp.float32(0.8),
                ways=128,
                use_pallas=True,
                count_health=True,
            )
            over = _unsort(d.code, order) == 2
            return state, jnp.packbits(over), health

        timed("decided_pallas", v8)

    print(json.dumps(results))


if __name__ == "__main__":
    main()
