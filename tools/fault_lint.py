#!/usr/bin/env python3
"""Fault-site lint: registry docstring <-> wired fire() sites <-> tests.

testing/faults.py documents the chaos-site registry (the contract the
FAULT_INJECT grammar, POST /debug/faults, and the chaos nemesis menu
all draw from). This lint cross-checks three views of that registry:

    documented   site names parsed from the faults.py registry docstring
    wired        sites that actually reach a FaultInjector.fire() call —
                 either a literal .fire("site") or a FAULT_SITE_*
                 constant fired in its defining module
    tested       sites named in at least one tests/*.py file

and fails on any asymmetry: a documented site nobody fires (dead
documentation), a fired site the docstring hides (unreviewable chaos
surface), or a site no test exercises (a fault arm that can rot).

Exit 0 clean, 1 findings, 2 usage. Wired into tier-1 via
tests/test_chaos_engine.py.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "api_ratelimit_tpu")
TESTS = os.path.join(REPO, "tests")

# a registry docstring row: indented site name, two+ spaces, prose
_DOC_SITE = re.compile(r"^\s{4}([a-z][a-z_]*(?:\.[a-z_]+)+)\s{2,}\S")
_CONST = re.compile(r'^(FAULT_SITE_\w+)\s*=\s*"([a-z_.]+)"', re.M)
_FIRE_LITERAL = re.compile(r'\.fire\(\s*\n?\s*"([a-z_.]+)"')
_FIRE_CONST = re.compile(r"\.fire\(\s*\n?\s*(FAULT_SITE_\w+)")


def documented_sites() -> set:
    import api_ratelimit_tpu.testing.faults as faults

    sites = set()
    for line in (faults.__doc__ or "").splitlines():
        match = _DOC_SITE.match(line)
        if match:
            sites.add(match.group(1))
    return sites


def _py_files(root: str):
    for dirpath, _dirs, files in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for fname in files:
            if fname.endswith(".py"):
                yield os.path.join(dirpath, fname)


def wired_sites() -> set:
    sites = set()
    for path in _py_files(PKG):
        try:
            with open(path, "r", encoding="utf-8") as f:
                text = f.read()
        except OSError:
            continue
        sites.update(_FIRE_LITERAL.findall(text))
        consts = dict(_CONST.findall(text))
        for name in _FIRE_CONST.findall(text):
            if name in consts:
                sites.add(consts[name])
    return sites


def tested_sites(sites) -> dict:
    """site -> list of test files that mention it."""
    hits = {site: [] for site in sites}
    for path in _py_files(TESTS):
        try:
            with open(path, "r", encoding="utf-8") as f:
                text = f.read()
        except OSError:
            continue
        for site in sites:
            if site in text:
                hits[site].append(os.path.basename(path))
    return hits


def run() -> list:
    documented = documented_sites()
    wired = wired_sites()
    findings = []
    if not documented:
        return ["faults.py registry docstring parsed to ZERO sites — "
                "the docstring format or this lint's parser broke"]
    for site in sorted(documented - wired):
        findings.append(
            f"{site}: documented in testing/faults.py but never fired — "
            f"dead registry row or a lost fire() call"
        )
    for site in sorted(wired - documented):
        findings.append(
            f"{site}: fire()d in the package but missing from the "
            f"testing/faults.py registry docstring — document it"
        )
    for site, files in sorted(tested_sites(documented | wired).items()):
        if not files:
            findings.append(
                f"{site}: no tests/*.py mentions this site — every fault "
                f"arm needs at least one exercising test"
            )
    return findings


def main(argv=None) -> int:
    findings = run()
    for finding in findings:
        print(finding)
    if findings:
        print(f"fault_lint: {len(findings)} finding(s)")
        return 1
    sites = sorted(documented_sites())
    print(f"fault_lint: clean ({len(sites)} sites documented+wired+tested)")
    return 0


if __name__ == "__main__":
    sys.path.insert(0, REPO)
    sys.exit(main())
