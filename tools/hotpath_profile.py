"""Host-path profiler: cProfile over the flat_per_second request loop.

Answers "where does the host half of a should_rate_limit go?" with the
exact service stack bench.py's flat_per_second tier builds (same config,
same TPU-slab backend, same batch window), driven from ONE thread under
cProfile and printed as a top-N cumulative table:

    python -m tools.hotpath_profile                 # 2000 requests, top 25
    python -m tools.hotpath_profile -n 500 --top 10 --sort tottime
    python -m tools.hotpath_profile --legacy        # pin the pre-vectorization path
    python -m tools.hotpath_profile --dispatch      # profile the device-OWNER thread
    make profile

--dispatch profiles the dispatch loop's owner thread instead of the
request thread: the loop runs its take/pack/launch/redeem cycle under its
own cProfile (DISPATCH_PROFILE=1, backends/dispatch.py) while this thread
drives traffic, and the owner's table is printed after close(). The
`lock.acquire` line is the owner parked waiting for work/readbacks — the
idle headroom; everything else is real per-cycle dispatch cost.

Single-thread on purpose: cProfile instruments only the calling thread,
so the dispatcher/device threads show up as one honest
`lock.acquire` line (the time THIS thread spends waiting on the launch
round trip) instead of half-attributed noise. Use `--pyinstrument` for a
wall-clock sampling view when that package is installed.

Output contract (pinned by tests/test_tools_platform.py): a
`[hotpath] rate=<N>/s requests=<N>` summary line, then the standard
pstats table whose header row contains `ncalls  tottime`.
"""

from __future__ import annotations

import argparse
import cProfile
import io
import os
import pstats
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("-n", type=int, default=2000, help="requests to drive")
    parser.add_argument("--top", type=int, default=25, help="rows to print")
    parser.add_argument(
        "--sort",
        default="cumulative",
        choices=["cumulative", "tottime", "ncalls"],
    )
    parser.add_argument(
        "--legacy",
        action="store_true",
        help="pin the legacy per-object host path (the A/B arm)",
    )
    parser.add_argument(
        "--dispatch",
        action="store_true",
        help="profile the dispatch loop's device-owner thread instead of "
        "the request thread (DISPATCH_PROFILE=1)",
    )
    parser.add_argument(
        "--frontend",
        action="store_true",
        help="profile one FRONTEND WORKER's hot loop end to end "
        "(decode -> match -> compose -> publish over shm rings to a "
        "local device owner) and print the native-vs-python split",
    )
    parser.add_argument(
        "--pyinstrument",
        action="store_true",
        help="wall-clock sampling profile instead of cProfile",
    )
    parser.add_argument(
        "--shard-split",
        action="store_true",
        help="print the ROUTED mesh dispatch owner's stage split "
        "(host bucket / pad+H2D / launch ns per mesh launch, "
        "parallel/sharded_slab.py shard_routing_snapshot) on a virtual "
        "CPU mesh, plus the per-shard row mix and padding waste",
    )
    parser.add_argument(
        "--shards",
        type=int,
        default=4,
        help="virtual mesh size for --shard-split (default 4)",
    )
    parser.add_argument(
        "--slab-split",
        action="store_true",
        help="print the slab stage-split baseline (set-gather / scan / "
        "scatter ns per launch, SlabDeviceEngine.profile_slab_split) "
        "instead of a host profile",
    )
    args = parser.parse_args(argv)

    sys.path.insert(0, REPO)
    if args.shard_split:
        # must run before anything imports jax: the forced device split
        # only takes effect at backend init
        return _run_shard_split(args)
    if args.frontend:
        return _run_frontend_profile(args)
    if args.dispatch:
        # must be set BEFORE the service (and its DispatchLoop thread)
        # is built: the owner thread reads it once at startup
        os.environ["DISPATCH_PROFILE"] = "1"
    import bench

    service, cache, _store = bench._build_service(
        "flat_per_second",
        bench._FLAT,
        telemetry=True,
        host_fast_path=not args.legacy,
    )
    reqs = bench._requests_for("flat_per_second", 2048)
    # warmup: compile/prime outside the profiled region
    for request in reqs[:64]:
        service.should_rate_limit(request)

    if args.slab_split:
        return _run_slab_split(cache, _store)
    if args.dispatch:
        return _run_dispatch_profile(service, cache, reqs, args)
    try:
        if args.pyinstrument:
            return _run_pyinstrument(service, reqs, args)
        prof = cProfile.Profile()
        t0 = time.perf_counter()
        prof.enable()
        for i in range(args.n):
            service.should_rate_limit(reqs[i % len(reqs)])
        prof.disable()
        elapsed = time.perf_counter() - t0
        print(
            f"[hotpath] rate={round(args.n / elapsed)}/s requests={args.n} "
            f"path={'legacy' if args.legacy else 'fast'}"
        )
        out = io.StringIO()
        stats = pstats.Stats(prof, stream=out)
        stats.sort_stats(args.sort).print_stats(args.top)
        print(out.getvalue())
        return 0
    finally:
        cache.close()


def _run_slab_split(cache, store) -> int:
    """The slab_split stage baseline: gather/scan/scatter per-launch ns
    on this box's geometry, recorded into (and reported from) the same
    ratelimit.slab.split.* runtime histograms bench.py publishes.

    Output contract (pinned by tests/test_tools_platform.py): one
    `[slab_split] batch=<N>` line, then `<stage>_ns p50=<N> p99=<N>`
    per stage."""
    try:
        engine = getattr(cache, "engine", None)
        if engine is None or not hasattr(engine, "profile_slab_split"):
            print("[slab_split] no slab engine in this build", file=sys.stderr)
            return 1
        result = engine.profile_slab_split(
            scope=store.scope("ratelimit").scope("slab"), iters=30
        )
        if not result:
            print("[slab_split] mesh engine: use tools/profile_engine.py",
                  file=sys.stderr)
            return 1
        import bench

        split = bench._slab_split(store)
        print(f"[slab_split] batch={result['batch']}")
        for stage in ("gather_ns", "scan_ns", "scatter_ns"):
            h = split.get(stage, {})
            print(
                f"  {stage:<11} p50={h.get('p50', result[stage])} "
                f"p99={h.get('p99', result[stage])}"
            )
        return 0
    finally:
        cache.close()


def _run_shard_split(args) -> int:
    """The routed dispatch owner's stage split on a virtual CPU mesh
    (SHARD_ROUTED_BATCHING, parallel/sharded_slab.py): host owner-hash +
    argsort (bucket), per-shard block fill + H2D (pad), and device
    dispatch (launch), per mesh launch, driven by a Zipf-skewed stream
    with the hot-key tier armed so the printout shows the shipped
    default's flattened shard mix.

    Output contract (pinned by tests/test_tools_platform.py): one
    `[shard_split] shards=<N> launches=<M>` line, a `<stage>_ns
    p50=<N> p99=<N>` row per stage, the per-shard routed row counts,
    and the cumulative `padding_waste_pct=`."""
    n_shards = max(2, int(args.shards))
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_shards}"
    ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax
    import numpy as np

    import bench
    from api_ratelimit_tpu.parallel.sharded_slab import (
        ShardedSlabEngine,
        make_mesh,
    )
    from api_ratelimit_tpu.ops.slab import (
        ROW_DIVIDER,
        ROW_FP_HI,
        ROW_FP_LO,
        ROW_HITS,
        ROW_LIMIT,
        ROW_SCALARS,
    )

    devices = jax.devices()[:n_shards]
    if len(devices) < 2:
        print(
            f"[shard_split] needs >=2 devices, got {len(devices)} "
            "(is another jax backend already initialized?)",
            file=sys.stderr,
        )
        return 1
    engine = ShardedSlabEngine(
        mesh=make_mesh(devices),
        n_slots_global=len(devices) * (1 << 13),
        routed=True,
        hot_tier=True,
        hotkey_lanes=128,
        hotkey_k=16,
        hot_min_count=200,
    )
    batch = 8192
    now = int(time.time())
    ids = bench.zipf_ids(50_000, batch, 6, seed=1)

    def pack(block_ids: np.ndarray) -> np.ndarray:
        p = np.zeros((7, block_ids.size), dtype=np.uint32)
        x = block_ids.astype(np.uint32)
        p[ROW_FP_LO] = bench.fmix32_np(x)
        p[ROW_FP_HI] = bench.fmix32_np(x ^ np.uint32(0xA5A5A5A5))
        p[ROW_HITS] = 1
        p[ROW_LIMIT] = 100
        p[ROW_DIVIDER] = 60
        p[ROW_SCALARS, 0] = np.uint32(now)
        p[ROW_SCALARS, 1] = np.float32(0.8).view(np.uint32)
        return p

    # block 0 warms the compile and feeds the sketch; the drain promotes
    # the Zipf head so the timed launches run the shipped default
    engine.step_after_compact(pack(ids[0]), 0xFFFF)
    engine.drain_hotkeys()
    for i in range(1, 6):
        engine.step_after_compact(pack(ids[i]), 0xFFFF)

    snap = engine.shard_routing_snapshot()
    print(f"[shard_split] shards={snap['shards']} launches={snap['launches']}")
    for stage in ("bucket_ns", "pad_ns", "launch_ns"):
        h = snap["stage_ns"][stage]
        print(f"  {stage:<10} p50={h.get('p50', 0)} p99={h.get('p99', 0)}")
    print(f"  shard_rows {snap['shard_rows']}")
    print(
        f"  padding_waste_pct={snap['padding_waste_pct']} "
        f"hot_keys={snap['hot_tier']['keys']}"
    )
    return 0


def _run_dispatch_profile(service, cache, reqs, args) -> int:
    """Drive traffic from a small thread pool (the owner loop only earns
    its keep under concurrency) and print the OWNER thread's cProfile."""
    from concurrent.futures import ThreadPoolExecutor

    loop = getattr(cache.engine, "_dispatch", None)
    if loop is None:
        print(
            "[hotpath] dispatch loop is not active (DISPATCH_LOOP off or "
            "direct mode); nothing to profile",
            file=sys.stderr,
        )
        cache.close()
        return 2

    def worker(tid: int) -> None:
        my = reqs[tid::4]
        for i in range(args.n // 4):
            service.should_rate_limit(my[i % len(my)])

    t0 = time.perf_counter()
    with ThreadPoolExecutor(4) as ex:
        list(ex.map(worker, range(4)))
    elapsed = time.perf_counter() - t0
    cache.close()  # stops the owner thread; its profile is final now
    print(
        f"[hotpath] rate={round(args.n / elapsed)}/s requests={args.n} "
        f"path=dispatch-owner"
    )
    if loop._profile is None:
        print("[hotpath] owner thread recorded no profile", file=sys.stderr)
        return 2
    out = io.StringIO()
    stats = pstats.Stats(loop._profile, stream=out)
    stats.sort_stats(args.sort).print_stats(args.top)
    print(out.getvalue())
    return 0


def _run_frontend_profile(args) -> int:
    """The FRONTEND_PROCS worker's view: a sidecar-backed service whose
    submits publish over shm rings to a device owner (running here on
    background threads, so the profiled REQUEST thread sees exactly what
    a worker process's handler thread sees: transport decode -> compiled
    matcher -> key compose -> row write -> shm publish -> verdict spin).
    Prints the standard pstats table plus a [native_split] block: which
    hot-loop stages run native and the per-stage ns from the runtime
    histograms.

    Output contract (pinned by tests/test_tools_platform.py): the
    `[hotpath] ... path=frontend-shm` line, a `[native_split]` line, then
    the pstats header row."""
    import tempfile

    import numpy as np  # noqa: F401 - bench pulls it anyway

    import bench
    from api_ratelimit_tpu.backends.sidecar import (
        SidecarEngineClient,
        SlabSidecarServer,
    )
    from api_ratelimit_tpu.backends.tpu import SlabDeviceEngine, TpuRateLimitCache
    from api_ratelimit_tpu.limiter.base_limiter import BaseRateLimiter
    from api_ratelimit_tpu.ops import native
    from api_ratelimit_tpu.service.ratelimit import RateLimitService
    from api_ratelimit_tpu.stats.sinks import NullSink
    from api_ratelimit_tpu.stats.store import Store
    from api_ratelimit_tpu.utils.timeutil import RealTimeSource

    td = tempfile.mkdtemp()
    sock = os.path.join(td, "owner.sock")
    ctl = sock + ".shmctl"
    engine = SlabDeviceEngine(
        RealTimeSource(),
        n_slots=1 << 16,
        use_pallas=False,
        buckets=(8, 128, 1024),
        batch_window_seconds=0.0005,
        max_batch=8192,
        block_mode=True,
    )
    server = SlabSidecarServer(sock, engine, shm_control_path=ctl)
    store = Store(NullSink())
    scope = store.scope("ratelimit")
    client = SidecarEngineClient(sock, scope=scope, shm_control_path=ctl)
    cache = TpuRateLimitCache(
        BaseRateLimiter(RealTimeSource()), engine=client
    )
    service = RateLimitService(
        runtime=bench._StaticRuntime(bench._FLAT),
        cache=cache,
        stats_scope=scope.scope("service"),
        time_source=RealTimeSource(),
    )
    reqs = bench._requests_for("flat_per_second", 2048)
    for request in reqs[:64]:
        service.should_rate_limit(request)
    try:
        prof = cProfile.Profile()
        t0 = time.perf_counter()
        prof.enable()
        for i in range(args.n):
            service.should_rate_limit(reqs[i % len(reqs)])
        prof.disable()
        elapsed = time.perf_counter() - t0
        print(
            f"[hotpath] rate={round(args.n / elapsed)}/s "
            f"requests={args.n} path=frontend-shm"
        )
        config = service.get_current_config()
        matcher_native = bool(
            config is not None
            and getattr(config.compiled, "native_active", False)
        )
        shm_active = client._shm is not None and not client._shm.dead
        print(
            f"[native_split] codec={'native' if native.available() else 'python'} "
            f"matcher={'native' if matcher_native else 'python'} "
            f"submit={'shm' if shm_active else 'socket'}"
        )
        snap = store.debug_snapshot()
        for label, key in (
            ("matcher_ns", "ratelimit.service.host.matcher_ms"),
            ("key_compose_ns", "ratelimit.host.key_compose_ms"),
            ("pack_ns", "ratelimit.host.pack_ms"),
            ("shm_submit_ns", "ratelimit.sidecar.shm_ms"),
        ):
            p50 = snap.get(f"{key}.p50")
            p99 = snap.get(f"{key}.p99")
            if p50 is None:
                continue
            print(
                f"  {label:<15} p50={round(p50 * 1e6)} p99={round(p99 * 1e6)}"
            )
        out = io.StringIO()
        stats = pstats.Stats(prof, stream=out)
        stats.sort_stats(args.sort).print_stats(args.top)
        print(out.getvalue())
        return 0
    finally:
        cache.close()
        server.close()


def _run_pyinstrument(service, reqs, args) -> int:
    try:
        from pyinstrument import Profiler
    except ImportError:
        print(
            "[hotpath] pyinstrument is not installed in this environment; "
            "re-run without --pyinstrument",
            file=sys.stderr,
        )
        return 2
    profiler = Profiler()
    t0 = time.perf_counter()
    with profiler:
        for i in range(args.n):
            service.should_rate_limit(reqs[i % len(reqs)])
    elapsed = time.perf_counter() - t0
    print(f"[hotpath] rate={round(args.n / elapsed)}/s requests={args.n}")
    print(profiler.output_text(unicode=True, color=False))
    return 0


if __name__ == "__main__":
    sys.exit(main())
