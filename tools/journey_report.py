"""Render retained journeys from /debug/journeys into an offline report.

Input: the JSON body of GET /debug/journeys (or the SIGUSR2 stderr dump) —
a file path, or '-' for stdin. Output: a per-stage percentile breakdown
(how long journeys spent between successive pipeline stages: publish ->
take -> pack -> launch -> redeem -> scatter) and a top-N slowest table
with flags and trace ids, so a captured tail can be diagnosed without the
process that recorded it.

jax-free by design: this must run anywhere the JSON lands (a laptop, a CI
artifact browser), never needing the accelerator stack.

    python -m tools.journey_report journeys.json
    python -m tools.journey_report --top 20 journeys.json
    curl -s localhost:6070/debug/journeys | python -m tools.journey_report -
    python -m tools.journey_report --json journeys.json   # machine-readable
    python -m tools.journey_report --hot-only journeys.json  # hotkey tail

Hot-key view (ops/sketch.py heavy-hitter telemetry): journeys whose
request touched a descriptor the sketch ranked hot carry the "hotkey"
flag. --hot-only restricts the whole report to those journeys; the
default report additionally splits every per-stage percentile row into
hot vs cold populations, so "the p99 is the hot head contending" and
"the p99 is a cold-path stall" are distinguishable at a glance.
"""

from __future__ import annotations

import argparse
import json
import sys

# canonical stage order (tracing/journeys.py STAGES; duplicated here so the
# report stays importable without the package installed). lease_local is
# the frontend-local decide mark (backends/lease.py) — requests answered
# from a leased budget carry it INSTEAD of the device stage set.
STAGE_ORDER = (
    "lease_local",
    "publish",
    "take",
    "pack",
    "launch",
    "redeem",
    "scatter",
)

# tracing/journeys.py FLAG_HOTKEY, duplicated for the same reason
FLAG_HOTKEY = "hotkey"


def is_hot(journey: dict) -> bool:
    return FLAG_HOTKEY in (journey.get("flags") or ())


def _percentile(ordered: list[float], q: float) -> float:
    if not ordered:
        return 0.0
    return ordered[min(len(ordered) - 1, int(len(ordered) * q))]


def stage_deltas(journey: dict) -> dict[str, float]:
    """Per-stage durations in ms: the gap from the previous recorded stage
    (or the journey start) to each stage's timestamp, in canonical order.
    Stages a journey never reached are simply absent."""
    stages = journey.get("stages") or {}
    start_ns = journey.get("start_ns", 0)
    deltas: dict[str, float] = {}
    prev = start_ns
    for name in STAGE_ORDER:
        ns = stages.get(name)
        if ns is None:
            continue
        deltas[name] = max(0.0, (ns - prev) / 1e6)
        prev = ns
    return deltas


def collect_journeys(doc: dict) -> list[dict]:
    """Retained journeys from a /debug/journeys document (accepts a bare
    list too, for hand-assembled inputs)."""
    if isinstance(doc, list):
        return doc
    return list(doc.get("retained") or doc.get("journeys") or [])


def _summarize_stages(journeys: list[dict]) -> dict:
    per_stage: dict[str, list[float]] = {}
    for journey in journeys:
        for stage, ms in stage_deltas(journey).items():
            per_stage.setdefault(stage, []).append(ms)
    stage_summary = {}
    for stage in STAGE_ORDER:
        values = sorted(per_stage.get(stage, []))
        if not values:
            continue
        stage_summary[stage] = {
            "count": len(values),
            "p50_ms": round(_percentile(values, 0.50), 4),
            "p90_ms": round(_percentile(values, 0.90), 4),
            "p99_ms": round(_percentile(values, 0.99), 4),
            "max_ms": round(values[-1], 4),
        }
    return stage_summary


def build_report(doc: dict, top: int = 10, hot_only: bool = False) -> dict:
    journeys = collect_journeys(doc)
    if hot_only:
        journeys = [j for j in journeys if is_hot(j)]
    stage_summary = _summarize_stages(journeys)
    hot = [j for j in journeys if is_hot(j)]
    slowest = sorted(
        journeys, key=lambda j: j.get("duration_ms", 0.0), reverse=True
    )[: max(0, top)]
    report = {
        "journeys": len(journeys),
        "hot_journeys": len(hot),
        "live_p99_ms": doc.get("live_p99_ms") if isinstance(doc, dict) else None,
        "stages": stage_summary,
        "slowest": [
            {
                "duration_ms": j.get("duration_ms", 0.0),
                "flags": j.get("flags", []),
                "kind": j.get("kind", ""),
                "trace_id": j.get("trace_id", ""),
                "thread": j.get("thread", ""),
                "stage_ms": {
                    k: round(v, 4) for k, v in stage_deltas(j).items()
                },
            }
            for j in slowest
        ],
    }
    # the hot/cold per-stage split (omitted under --hot-only, where the
    # whole report IS the hot population): same percentile rows computed
    # over the two sub-populations, so a fat device stage can be
    # attributed to head contention vs cold-path stalls
    if not hot_only and hot and len(hot) < len(journeys):
        cold = [j for j in journeys if not is_hot(j)]
        report["stages_hot"] = _summarize_stages(hot)
        report["stages_cold"] = _summarize_stages(cold)
    return report


def _stage_table(stages: dict, header: str | None = None) -> list[str]:
    lines = []
    if header:
        lines.append(header)
    lines.append(
        f"{'stage':<10} {'count':>6} {'p50_ms':>10} {'p90_ms':>10} "
        f"{'p99_ms':>10} {'max_ms':>10}"
    )
    for stage in STAGE_ORDER:
        s = stages.get(stage)
        if s is None:
            continue
        lines.append(
            f"{stage:<10} {s['count']:>6} {s['p50_ms']:>10.4f} "
            f"{s['p90_ms']:>10.4f} {s['p99_ms']:>10.4f} {s['max_ms']:>10.4f}"
        )
    return lines


def render_text(report: dict) -> str:
    lines = [
        f"[journeys] retained={report['journeys']} "
        f"hot={report.get('hot_journeys', 0)}"
    ]
    if report.get("live_p99_ms") is not None:
        lines[0] += f" live_p99={report['live_p99_ms']:.3f}ms"
    lines.append("")
    lines.extend(_stage_table(report["stages"]))
    if report.get("stages_hot"):
        lines.append("")
        lines.extend(
            _stage_table(report["stages_hot"], "hot (flagged 'hotkey'):")
        )
        lines.append("")
        lines.extend(_stage_table(report["stages_cold"], "cold:"))
    lines.append("")
    lines.append(f"top {len(report['slowest'])} slowest:")
    lines.append(
        f"{'duration_ms':>12}  {'flags':<24} {'kind':<16} "
        f"{'trace_id':<34} stages"
    )
    for j in report["slowest"]:
        stage_txt = " ".join(
            f"{k}={v:.3f}" for k, v in j["stage_ms"].items()
        )
        lines.append(
            f"{j['duration_ms']:>12.3f}  {','.join(j['flags']) or '-':<24} "
            f"{j['kind']:<16} {j['trace_id'] or '-':<34} {stage_txt}"
        )
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Render /debug/journeys output offline"
    )
    parser.add_argument(
        "input", help="path to the /debug/journeys JSON, or '-' for stdin"
    )
    parser.add_argument(
        "--top", type=int, default=10, help="slowest journeys to list"
    )
    parser.add_argument(
        "--json", action="store_true", help="emit the report as JSON"
    )
    parser.add_argument(
        "--hot-only",
        action="store_true",
        help="restrict the report to journeys flagged 'hotkey' (requests "
        "that touched a sketch-ranked heavy hitter)",
    )
    args = parser.parse_args(argv)
    try:
        if args.input == "-":
            doc = json.load(sys.stdin)
        else:
            with open(args.input, encoding="utf-8") as f:
                doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"journey_report: cannot read {args.input}: {e}", file=sys.stderr)
        return 1
    report = build_report(doc, top=args.top, hot_only=args.hot_only)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(render_text(report), end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
