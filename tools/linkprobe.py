"""Characterize the host<->device link before trusting any measurement.

The 2026-07-31 chip window died mid-way through engine_ab2's staging: the
process sat 21 minutes at 1s of CPU, blocked in a device_put, with no way
to tell whether the tunnel had died or a large transfer was crawling.
This probe ramps transfer sizes 1MB -> 256MB with a flushed line per
size, so the log always shows the largest size that completed and the
realized bandwidth in each direction. Run it FIRST in any chip window.
"""

from __future__ import annotations

import sys
import time

import numpy as np


def main() -> None:
    from api_ratelimit_tpu.utils.jaxsetup import respect_jax_platforms_env

    respect_jax_platforms_env()
    import jax

    t0 = time.perf_counter()
    d = jax.devices()[0]
    print(
        f"[linkprobe] device={d} platform={d.platform} "
        f"init={time.perf_counter() - t0:.1f}s",
        file=sys.stderr,
        flush=True,
    )
    sizes = [1, 4, 16, 64, 256]
    if d.platform != "tpu":
        sizes = [1, 4]
    # Connection warmup (as bench.py's measure_link does): the first
    # transfer pays one-time tunnel/client setup that would otherwise be
    # billed to the 1MB row and misread as a slow link.
    w = jax.device_put(np.zeros(1024, dtype=np.int32), d)
    np.asarray(w)
    del w
    results = {"platform": d.platform}
    for mb in sizes:
        a = np.zeros((mb << 20) // 4, dtype=np.int32)
        t0 = time.perf_counter()
        x = jax.device_put(a, d)
        x.block_until_ready()
        h2d = time.perf_counter() - t0
        t0 = time.perf_counter()
        np.asarray(x)
        d2h = time.perf_counter() - t0
        results[f"{mb}MB"] = {
            "h2d_MBps": round(mb / h2d, 1),
            "d2h_MBps": round(mb / d2h, 1),
        }
        print(
            f"[linkprobe] {mb}MB h2d {mb / h2d:.1f} MB/s ({h2d:.2f}s) "
            f"d2h {mb / d2h:.1f} MB/s ({d2h:.2f}s)",
            file=sys.stderr,
            flush=True,
        )
        del x
    # One tiny dispatch round-trip: the per-launch floor every
    # chained-step measurement sits on top of.
    import jax.numpy as jnp

    f = jax.jit(lambda v: v + 1)
    y = jax.device_put(np.zeros(8, dtype=np.int32), d)
    f(y).block_until_ready()
    t0 = time.perf_counter()
    n = 20
    for _ in range(n):
        y = f(y)
    y.block_until_ready()
    chained = (time.perf_counter() - t0) / n * 1e3
    t0 = time.perf_counter()
    for _ in range(n):
        f(y).block_until_ready()
    blocking = (time.perf_counter() - t0) / n * 1e3
    results["launch_ms_chained"] = round(chained, 3)
    results["launch_ms_blocking"] = round(blocking, 3)
    print(
        f"[linkprobe] launch chained {chained:.3f}ms blocking {blocking:.3f}ms",
        file=sys.stderr,
        flush=True,
    )
    import json

    print(json.dumps(results), flush=True)


if __name__ == "__main__":
    main()
