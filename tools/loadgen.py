"""Distributed closed-loop HTTP load generator for the frontend fleet.

A single-process driver cannot saturate a FRONTEND_PROCS fleet: the
fleet exists to split the GIL across processes, so a load plane sharing
one GIL measures itself. This generator spawns N worker PROCESSES (each
its own interpreter via ``-m tools.loadgen --worker``), each running M
closed-loop threads that POST v3 RateLimitRequest JSON to the fleet's
shared ``/json`` port; per-process latency histograms on the service's
own bucket ladder (stats/store.py DEFAULT_LATENCY_BUCKETS_MS) are
written to report files and merged client-side — bucket counts are
additive across processes, exactly like the server-side fleet merge in
stats/fleet.py. When ``fleet_metrics_url`` is given, the run brackets
the measured window with fleet scrapes and reports the server-side
decision-counter delta next to the client-observed rate, so over- or
under-counting on either side is visible in one artifact.

jax-free and stdlib-only (urllib): the load plane must boot in
milliseconds and never compete with the fleet for an accelerator.

Usage:
    python -m tools.loadgen --url http://127.0.0.1:8080/json \
        --procs 4 --threads 4 --seconds 5 --domain bench --key api_key
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# the service's own latency ladder (stats/store.py) so client-side and
# server-side histograms line up bucket for bucket
from api_ratelimit_tpu.stats.store import DEFAULT_LATENCY_BUCKETS_MS


def _new_hist() -> list:
    # one count per finite bucket + one overflow slot (+Inf)
    return [0] * (len(DEFAULT_LATENCY_BUCKETS_MS) + 1)


def _observe(hist: list, ms: float) -> None:
    for i, edge in enumerate(DEFAULT_LATENCY_BUCKETS_MS):
        if ms <= edge:
            hist[i] += 1
            return
    hist[-1] += 1


def merge_hists(hists) -> list:
    merged = _new_hist()
    for h in hists:
        for i, c in enumerate(h):
            merged[i] += c
    return merged


def percentile_from_hist(hist: list, q: float) -> float:
    """Upper-bound percentile estimate off the bucket counts (the same
    conservative read a Prometheus scrape of the ladder would give).
    Returns the +Inf bucket as the last finite edge."""
    total = sum(hist)
    if not total:
        return 0.0
    rank = q * total
    seen = 0
    for i, c in enumerate(hist):
        seen += c
        if seen >= rank:
            if i < len(DEFAULT_LATENCY_BUCKETS_MS):
                return float(DEFAULT_LATENCY_BUCKETS_MS[i])
            return float(DEFAULT_LATENCY_BUCKETS_MS[-1])
    return float(DEFAULT_LATENCY_BUCKETS_MS[-1])


def _request_body(domain: str, key: str, value: str) -> bytes:
    return json.dumps(
        {
            "domain": domain,
            "descriptors": [{"entries": [{"key": key, "value": value}]}],
        }
    ).encode()


def run_worker_process(spec: dict) -> dict:
    """One driver process: closed-loop threads against the fleet port.
    Per-status counts + one merged latency histogram; 429s are SUCCESSES
    for the load plane (the limiter answered), transport errors are not."""
    try:
        # per-process pin from the parent's affinity plan (best-effort)
        aff = os.environ.get("BENCH_CPU_AFFINITY", "").strip()
        if aff:
            os.sched_setaffinity(0, {int(c) for c in aff.split(",")})
    except (AttributeError, ValueError, OSError):
        pass
    url = spec["url"]
    n_threads = int(spec["threads"])
    duration = float(spec["duration_s"])
    bodies = [
        _request_body(spec["domain"], spec["key"], f"k{i}")
        for i in range(int(spec["n_keys"]))
    ]
    hist = _new_hist()
    status_counts: dict = {}
    errors = [0]
    lock = threading.Lock()
    t_end = time.monotonic() + duration

    def worker(tid: int) -> None:
        local_hist = _new_hist()
        local_status: dict = {}
        local_errors = 0
        my = bodies[tid::n_threads] or bodies
        i = 0
        while time.monotonic() < t_end:
            body = my[i % len(my)]
            i += 1
            req = urllib.request.Request(
                url, data=body, headers={"Content-Type": "application/json"}
            )
            t0 = time.perf_counter()
            try:
                with urllib.request.urlopen(req, timeout=5.0) as resp:  # noqa: S310
                    resp.read()
                    code = resp.status
            except urllib.error.HTTPError as e:
                e.read()
                code = e.code
            except Exception:  # noqa: BLE001 - transport failure IS the metric
                local_errors += 1
                continue
            _observe(local_hist, (time.perf_counter() - t0) * 1e3)
            local_status[code] = local_status.get(code, 0) + 1
        with lock:
            for j, c in enumerate(local_hist):
                hist[j] += c
            for code, c in local_status.items():
                status_counts[code] = status_counts.get(code, 0) + c
            errors[0] += local_errors

    threads = [
        threading.Thread(target=worker, args=(t,)) for t in range(n_threads)
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.monotonic() - t0
    return {
        "pid": os.getpid(),
        "n": sum(hist),
        "elapsed_s": round(elapsed, 3),
        "hist": hist,
        "status_counts": {str(k): v for k, v in status_counts.items()},
        "transport_errors": errors[0],
    }


def _scrape(url: str, timeout: float = 3.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as resp:  # noqa: S310
        return resp.read().decode("utf-8", errors="replace")


def _counter_totals(text: str) -> dict:
    """Fleet-exposition counter totals (plus histogram/summary _count
    series), keyed by sample name — the server-side half of the pairing."""
    from api_ratelimit_tpu.stats import fleet

    _types, families = fleet.parse_exposition(text)
    totals: dict = {}
    for name, samples in families.items():
        kind = _types.get(name, "")
        for key, value in samples.items():
            if kind == "counter" or key.endswith("_count"):
                totals[key] = totals.get(key, 0.0) + value
    return totals


def run_distributed(
    url: str,
    procs: int,
    threads: int,
    duration_s: float,
    domain: str = "bench",
    key: str = "api_key",
    n_keys: int = 512,
    fleet_metrics_url: str | None = None,
    affinity_plan=None,
) -> dict:
    """Spawn ``procs`` worker processes, merge their report files, and
    (optionally) bracket the window with server-side fleet scrapes."""
    spec = {
        "url": url,
        "threads": threads,
        "duration_s": duration_s,
        "domain": domain,
        "key": key,
        "n_keys": n_keys,
    }
    before = None
    if fleet_metrics_url:
        try:
            before = _counter_totals(_scrape(fleet_metrics_url))
        except Exception:  # noqa: BLE001 - scrape is evidence, not a gate
            before = None
    workers = []
    outs = []
    td = tempfile.mkdtemp(prefix="loadgen-")
    for i in range(procs):
        out_path = os.path.join(td, f"w{i}.json")
        outs.append(out_path)
        env = dict(os.environ)
        if affinity_plan is not None and i < len(affinity_plan):
            env["BENCH_CPU_AFFINITY"] = ",".join(
                str(c) for c in affinity_plan[i]
            )
        workers.append(
            subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "tools.loadgen",
                    "--worker",
                    json.dumps({**spec, "out": out_path}),
                ],
                cwd=REPO,
                env=env,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.STDOUT,
            )
        )
    reports = []
    deadline = time.monotonic() + duration_s + 120.0
    for w, out_path in zip(workers, outs):
        try:
            w.wait(timeout=max(1.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            w.kill()
            w.wait()
        if os.path.exists(out_path):
            with open(out_path) as f:
                reports.append(json.load(f))
    after = None
    if fleet_metrics_url and before is not None:
        try:
            after = _counter_totals(_scrape(fleet_metrics_url))
        except Exception:  # noqa: BLE001
            after = None
    hist = merge_hists([r["hist"] for r in reports])
    n = sum(hist)
    elapsed = max((r["elapsed_s"] for r in reports), default=0.0)
    status: dict = {}
    for r in reports:
        for code, c in r["status_counts"].items():
            status[code] = status.get(code, 0) + c
    result = {
        "procs": procs,
        "procs_reporting": len(reports),
        "threads_per_proc": threads,
        "n": n,
        "rate": round(n / elapsed) if elapsed else 0,
        "p50_ms": percentile_from_hist(hist, 0.50),
        "p99_ms": percentile_from_hist(hist, 0.99),
        "hist_buckets_ms": list(DEFAULT_LATENCY_BUCKETS_MS),
        "hist": hist,
        "status_counts": status,
        "transport_errors": sum(r["transport_errors"] for r in reports),
    }
    if before is not None and after is not None:
        deltas = {
            k: round(after[k] - before.get(k, 0.0), 3)
            for k in after
            if after[k] - before.get(k, 0.0) > 0
        }
        # the headline pairing: what the SERVERS counted over the window
        # next to what the CLIENTS observed
        result["fleet_counter_deltas"] = dict(
            sorted(deltas.items(), key=lambda kv: -kv[1])[:24]
        )
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--worker", help="internal: run one worker process")
    ap.add_argument("--url", default="http://127.0.0.1:8080/json")
    ap.add_argument("--procs", type=int, default=2)
    ap.add_argument("--threads", type=int, default=4)
    ap.add_argument("--seconds", type=float, default=5.0)
    ap.add_argument("--domain", default="bench")
    ap.add_argument("--key", default="api_key")
    ap.add_argument("--keys", type=int, default=512)
    ap.add_argument("--fleet-url", help="master GET /metrics?fleet=1 URL")
    args = ap.parse_args(argv)
    if args.worker:
        spec = json.loads(args.worker)
        report = run_worker_process(spec)
        tmp = spec["out"] + ".tmp"
        with open(tmp, "w") as f:
            json.dump(report, f)
        os.replace(tmp, spec["out"])
        return 0
    result = run_distributed(
        url=args.url,
        procs=args.procs,
        threads=args.threads,
        duration_s=args.seconds,
        domain=args.domain,
        key=args.key,
        n_keys=args.keys,
        fleet_metrics_url=args.fleet_url,
    )
    print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
