"""Lint stat-name registrations across the package.

Walks api_ratelimit_tpu/ for literal stat registrations —
scope.counter("..."), .gauge("..."), .timer("..."), .histogram("...") —
and fails on:

  * names violating the dotted-lowercase convention
    (``segment.segment`` where a segment is ``[a-z0-9_]+``); and
  * the same literal name registered under CONFLICTING stat kinds
    (e.g. a counter somewhere and a gauge elsewhere): the Prometheus
    renderer would emit two # TYPE declarations for one family, which
    scrapers reject.

Names are literals as written at the call site (scope-relative); the
convention check is what keeps the composed dotted paths well-formed.
Dynamically composed names (f-strings, variables) are out of scope.

Run standalone (``python tools/metrics_lint.py``; exit 1 on findings) or
via the fast pytest wrapper in tests/test_metrics_lint.py, which is part
of the tier-1 run. No jax import — this must stay cheap.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "api_ratelimit_tpu")

_REGISTRATION = re.compile(
    r"\.(?P<kind>counter|gauge|timer|histogram)\(\s*(?P<q>['\"])(?P<name>[^'\"]+)(?P=q)"
)
_NAME_OK = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)*$")

# freecache parity names (limiter/local_cache.py): the reference exports
# the Go library's camelCase counters verbatim so existing dashboards and
# the prom-statsd-exporter mapping carry over (README "Switching from
# kentik/api-ratelimit"); exempt from the convention, not from the
# conflicting-kind check.
NAME_ALLOWLIST = frozenset(
    {
        "hitCount",
        "missCount",
        "lookupCount",
        "entryCount",
        "expiredCount",
        "evacuateCount",
        "overwriteCount",
    }
)


def iter_registrations(package_dir: str = PACKAGE):
    """Yield (name, kind, file, line) for every literal registration."""
    for dirpath, dirnames, filenames in os.walk(package_dir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for filename in sorted(filenames):
            if not filename.endswith(".py"):
                continue
            path = os.path.join(dirpath, filename)
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, start=1):
                    for m in _REGISTRATION.finditer(line):
                        yield (
                            m.group("name"),
                            m.group("kind"),
                            os.path.relpath(path, REPO),
                            lineno,
                        )


def lint(package_dir: str = PACKAGE) -> list[str]:
    """Returns a list of human-readable findings (empty = clean)."""
    findings: list[str] = []
    kinds_by_name: dict[str, dict[str, list[str]]] = {}
    for name, kind, path, lineno in iter_registrations(package_dir):
        site = f"{path}:{lineno}"
        if name not in NAME_ALLOWLIST and not _NAME_OK.match(name):
            findings.append(
                f"{site}: stat name {name!r} violates the dotted-lowercase "
                f"convention ([a-z0-9_] segments joined by '.')"
            )
        kinds_by_name.setdefault(name, {}).setdefault(kind, []).append(site)
    for name, kinds in sorted(kinds_by_name.items()):
        if len(kinds) > 1:
            detail = "; ".join(
                f"{kind} at {', '.join(sites)}" for kind, sites in sorted(kinds.items())
            )
            findings.append(
                f"stat name {name!r} registered with conflicting types: {detail}"
            )
    return findings


def main() -> int:
    findings = lint()
    if findings:
        for finding in findings:
            print(f"metrics-lint: {finding}", file=sys.stderr)
        print(f"metrics-lint: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    count = sum(1 for _ in iter_registrations())
    print(f"metrics-lint: OK ({count} literal registrations checked)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
