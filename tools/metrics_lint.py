"""Lint stat-name registrations across the package.

Walks api_ratelimit_tpu/ for literal stat registrations —
scope.counter("..."), .gauge("..."), .timer("..."), .histogram("...") —
and fails on:

  * names violating the dotted-lowercase convention
    (``segment.segment`` where a segment is ``[a-z0-9_]+``); and
  * the same literal name registered under CONFLICTING stat kinds
    (e.g. a counter somewhere and a gauge elsewhere): the Prometheus
    renderer would emit two # TYPE declarations for one family, which
    scrapers reject.

Names are literals as written at the call site (scope-relative); the
convention check is what keeps the composed dotted paths well-formed.
Dynamically composed names (f-strings, variables) are out of scope.

It also drift-checks the README: every backticked ``ratelimit.*`` metric
name mentioned in README.md (brace alternations like ``{steals,drops}``
expanded; ``<placeholder>`` tokens skipped) must resolve to a literal
registration in the source — a renamed or deleted stat must not leave a
stale name in the operator docs.

Run standalone (``python tools/metrics_lint.py``; exit 1 on findings) or
via the fast pytest wrapper in tests/test_metrics_lint.py, which is part
of the tier-1 run. No jax import — this must stay cheap.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "api_ratelimit_tpu")
README = os.path.join(REPO, "README.md")

_REGISTRATION = re.compile(
    r"\.(?P<kind>counter|gauge|timer|histogram)\(\s*(?P<q>['\"])(?P<name>[^'\"]+)(?P=q)"
)
_NAME_OK = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)*$")

# freecache parity names (limiter/local_cache.py): the reference exports
# the Go library's camelCase counters verbatim so existing dashboards and
# the prom-statsd-exporter mapping carry over (README "Switching from
# kentik/api-ratelimit"); exempt from the convention, not from the
# conflicting-kind check.
NAME_ALLOWLIST = frozenset(
    {
        "hitCount",
        "missCount",
        "lookupCount",
        "entryCount",
        "expiredCount",
        "evacuateCount",
        "overwriteCount",
    }
)


def iter_registrations(package_dir: str = PACKAGE):
    """Yield (name, kind, file, line) for every literal registration."""
    for dirpath, dirnames, filenames in os.walk(package_dir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for filename in sorted(filenames):
            if not filename.endswith(".py"):
                continue
            path = os.path.join(dirpath, filename)
            with open(path, encoding="utf-8") as f:
                text = f.read()
            # whole-file scan: \s* spans newlines, so a registration whose
            # string literal sits on a continuation line still counts
            for m in _REGISTRATION.finditer(text):
                yield (
                    m.group("name"),
                    m.group("kind"),
                    os.path.relpath(path, REPO),
                    text.count("\n", 0, m.start()) + 1,
                )


def lint(package_dir: str = PACKAGE) -> list[str]:
    """Returns a list of human-readable findings (empty = clean)."""
    findings: list[str] = []
    kinds_by_name: dict[str, dict[str, list[str]]] = {}
    for name, kind, path, lineno in iter_registrations(package_dir):
        site = f"{path}:{lineno}"
        if name not in NAME_ALLOWLIST and not _NAME_OK.match(name):
            findings.append(
                f"{site}: stat name {name!r} violates the dotted-lowercase "
                f"convention ([a-z0-9_] segments joined by '.')"
            )
        kinds_by_name.setdefault(name, {}).setdefault(kind, []).append(site)
    for name, kinds in sorted(kinds_by_name.items()):
        if len(kinds) > 1:
            detail = "; ".join(
                f"{kind} at {', '.join(sites)}" for kind, sites in sorted(kinds.items())
            )
            findings.append(
                f"stat name {name!r} registered with conflicting types: {detail}"
            )
    return findings


# backticked dotted stat paths in the README, e.g. `ratelimit.slab.loss_ppm`
# or `ratelimit.sidecar.{retry,redial}`; `<domain>`-style placeholders make
# a token unverifiable and are skipped
_README_METRIC = re.compile(r"`(ratelimit\.[A-Za-z0-9_.{},<>]+)`")
_BRACE = re.compile(r"\{([^{}]*)\}")


def readme_metric_names(readme_path: str = README) -> list[str]:
    """Concrete dotted stat names mentioned in the README (one level of
    {a,b,c} alternation expanded; placeholder tokens skipped)."""
    try:
        with open(readme_path, encoding="utf-8") as f:
            text = f.read()
    except FileNotFoundError:
        return []
    names: set[str] = set()
    for m in _README_METRIC.finditer(text):
        token = m.group(1)
        if "<" in token or ">" in token:
            continue
        expanded = [token]
        while any("{" in t for t in expanded):
            nxt = []
            for t in expanded:
                mm = _BRACE.search(t)
                if mm is None:
                    nxt.append(t)
                    continue
                for alt in mm.group(1).split(","):
                    nxt.append(t[: mm.start()] + alt.strip() + t[mm.end():])
            expanded = nxt
        names.update(expanded)
    return sorted(names)


def lint_readme(
    package_dir: str = PACKAGE, readme_path: str = README
) -> list[str]:
    """README drift check: every documented ratelimit.* metric must end in
    a literal stat name registered somewhere in the package (registrations
    are scope-relative, so the check is a dotted-suffix match)."""
    findings: list[str] = []
    literals = {name for name, _, _, _ in iter_registrations(package_dir)}
    for name in readme_metric_names(readme_path):
        if not any(
            name == lit or name.endswith("." + lit) for lit in literals
        ):
            findings.append(
                f"README.md: metric {name!r} does not match any literal "
                f"stat registration in the package (renamed or deleted?)"
            )
    return findings


def lint_exposition(text: str) -> list[str]:
    """Validate a Prometheus text exposition — in particular the merged
    fleet output of stats/fleet.py merge_expositions (the master's
    ``GET /metrics?fleet=1`` body): every sample must belong to a
    ``# TYPE``-declared family, no family may be declared twice, no
    sample name may repeat, and histogram bucket series must be
    cumulative (monotone non-decreasing toward ``+Inf``). A merge bug —
    double-declared families from conflicting member types, non-monotone
    buckets from summing absolutes into cumulatives — fails here before
    a scraper ever sees it."""
    findings: list[str] = []
    declared: dict[str, str] = {}
    seen_samples: set[str] = set()
    current: str | None = None
    bucket_last: dict[str, float] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4:
                findings.append(f"line {lineno}: malformed TYPE line {line!r}")
                continue
            name, kind = parts[2], parts[3]
            if name in declared:
                findings.append(
                    f"line {lineno}: family {name!r} declared twice"
                )
            declared[name] = kind
            current = name
            continue
        if line.startswith("#"):
            continue
        try:
            key, raw = line.rsplit(" ", 1)
            value = float(raw)
        except ValueError:
            findings.append(f"line {lineno}: malformed sample {line!r}")
            continue
        base = key.split("{", 1)[0]
        if current is None or not base.startswith(current):
            findings.append(
                f"line {lineno}: sample {key!r} has no owning # TYPE family"
            )
        if key in seen_samples:
            findings.append(f"line {lineno}: duplicate sample {key!r}")
        seen_samples.add(key)
        if base.endswith("_bucket") and "le=" in key:
            prev = bucket_last.get(base)
            if prev is not None and value < prev:
                findings.append(
                    f"line {lineno}: histogram {base!r} buckets are not "
                    f"cumulative ({value} after {prev})"
                )
            bucket_last[base] = value
    return findings


def main() -> int:
    findings = lint() + lint_readme()
    if findings:
        for finding in findings:
            print(f"metrics-lint: {finding}", file=sys.stderr)
        print(f"metrics-lint: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    count = sum(1 for _ in iter_registrations())
    print(f"metrics-lint: OK ({count} literal registrations checked)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
