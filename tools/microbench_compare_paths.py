"""Where are compares fast? XLA elementwise vs Mosaic (Pallas) kernels.

tools/microbench_isolate.py showed on this stack a bare XLA elementwise
compare over 2^20 elements costs ~9-27ms (vs 0.03ms gathers, 0.37ms sort) —
compare/select lowerings are the engine's real bottleneck, not data movement.
This measures the same logic compiled through Mosaic, plus which XLA op
classes exactly are slow (compare / select / int32 reduce / bool convert),
all with varied inputs per repeat.
"""

from __future__ import annotations

import argparse
import functools
import json
import sys
import time

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=1 << 20)
    ap.add_argument("--repeats", type=int, default=8)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    device = jax.devices()[0]
    b = args.batch
    if device.platform != "tpu" and b > (1 << 14):
        b = 1 << 13

    rng = np.random.RandomState(0)
    xs = [
        jax.device_put(
            rng.randint(0, 1 << 31, size=b).astype(np.int32), device
        )
        for _ in range(args.repeats)
    ]
    now = jnp.int32(1 << 30)
    results: dict = {"platform": device.platform, "batch": b}

    def timeit(label, f):
        out = f(xs[-1])
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        outs = [f(x) for x in xs]
        jax.block_until_ready(outs)
        ms = round((time.perf_counter() - t0) / len(xs) * 1e3, 3)
        results[label] = ms
        print(f"[cmp-paths] {label}: {ms}ms", file=sys.stderr)

    # --- XLA op-class isolation ---
    timeit("xla_sum_u32", jax.jit(lambda x: x.astype(jnp.uint32).sum()))
    timeit("xla_sum_i32", jax.jit(lambda x: x.sum()))
    timeit("xla_add_out", jax.jit(lambda x: x + jnp.int32(1)))  # no compare
    timeit("xla_cmp_out", jax.jit(lambda x: (x > now).astype(jnp.int32)))
    timeit("xla_sel_out", jax.jit(lambda x: jnp.where(x > now, x, -x)))
    timeit("xla_min_out", jax.jit(lambda x: jnp.minimum(x, now)))
    # arithmetic-only mask blend (the compare-free alternative)
    timeit(
        "xla_arith_mask_out",
        jax.jit(lambda x: (x & ((now - x) >> 31)) | (-x & ~((now - x) >> 31))),
    )

    # --- the same compare+select through a Mosaic kernel ---
    LANES = 128
    rows = b // LANES

    NOW = 1 << 30  # python literal: lowers as an immediate, no capture

    def sel_kernel(x_ref, o_ref):
        x = x_ref[...]
        o_ref[...] = jnp.where(x > NOW, x, -x)

    block = min(rows, 256)

    @jax.jit
    def pallas_sel(x):
        x2 = x.reshape(rows, LANES)
        return pl.pallas_call(
            sel_kernel,
            grid=(rows // block,),
            in_specs=[pl.BlockSpec((block, LANES), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((block, LANES), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.int32),
        )(x2)

    try:
        timeit("pallas_sel_out", pallas_sel)
    except Exception as e:
        results["pallas_sel_error"] = str(e)[-200:]

    # several compares + selects fused in one kernel (probe-select shape)
    def chain_kernel(x_ref, o_ref):
        x = x_ref[...]
        m1 = x > NOW
        m2 = (x & 7) == 3
        m3 = x < (NOW >> 1)
        r = jnp.where(m1, x, -x)
        r = jnp.where(m2, r + 1, r)
        r = jnp.where(m3 & m1, r ^ 21, r)
        o_ref[...] = r

    @jax.jit
    def pallas_chain(x):
        x2 = x.reshape(rows, LANES)
        return pl.pallas_call(
            chain_kernel,
            grid=(rows // block,),
            in_specs=[pl.BlockSpec((block, LANES), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((block, LANES), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.int32),
        )(x2)

    try:
        timeit("pallas_chain_out", pallas_chain)
    except Exception as e:
        results["pallas_chain_error"] = str(e)[-200:]

    # XLA twin of the chain for the head-to-head
    @jax.jit
    def xla_chain(x):
        m1 = x > now
        m2 = (x & 7) == 3
        m3 = x < (now >> 1)
        r = jnp.where(m1, x, -x)
        r = jnp.where(m2, r + 1, r)
        r = jnp.where(m3 & m1, r ^ 21, r)
        return r

    timeit("xla_chain_out", xla_chain)

    print(json.dumps(results))


if __name__ == "__main__":
    main()
