"""Cost-model microbench for the slab's data-movement primitives.

The r4 hardware profile (tools/profile_engine.py) showed the engine step is
dominated by gather/scatter, not the sort: probe gather ~131ms of a ~294ms
step at batch 2^20 over a [2^23, 8] table. Before redesigning the slab
layout, this measures each candidate primitive in isolation so the choice
is driven by the chip's actual gather cost model (per-element overhead vs
bytes moved), not guesses:

  * flat u32 gather from [n]             (structure-of-arrays probe)
  * row gather from [n, 8]               (current fused-row probe)
  * 4-candidate row gather (b,4) idx     (current probe shape)
  * bucket gather from [n/4, 32]         (4-way set-associative probe:
                                          one 128B fetch covers 4 ways)
  * bucket gather from [n/16, 128]       (16-way, one full 512B lane row)
  * row scatter to [n, 8]                (current write-back)
  * bucket scatter to [n/16, 128]
  * 2-operand lax.sort at 2^20           (duplicate grouping)
  * permutation gather (order apply)     (the post-sort operand permute)

Usage:  python tools/microbench_gather.py [--batch 1048576] [--slots 8388608]
Prints one JSON object of stage -> ms/call.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=1 << 20)
    ap.add_argument("--slots", type=int, default=1 << 23)
    ap.add_argument("--repeats", type=int, default=10)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    device = jax.devices()[0]
    if device.platform != "tpu" and args.batch > (1 << 14):
        args.batch, args.slots = 1 << 13, 1 << 18

    b, n = args.batch, args.slots
    rng = np.random.RandomState(0)
    idx_np = rng.randint(0, n, size=b).astype(np.int32)
    cand_np = rng.randint(0, n, size=(b, 4)).astype(np.int32)

    idx = jax.device_put(idx_np, device)
    cand = jax.device_put(cand_np, device)
    tab1 = jax.device_put(np.zeros(n, np.uint32), device)
    tab8 = jax.device_put(np.zeros((n, 8), np.uint32), device)
    tab32 = jax.device_put(np.zeros((n // 4, 32), np.uint32), device)
    tab128 = jax.device_put(np.zeros((n // 16, 128), np.uint32), device)
    idx4 = jax.device_put((idx_np // 4).astype(np.int32), device)
    idx16 = jax.device_put((idx_np // 16).astype(np.int32), device)
    rows_np = np.zeros((b, 8), np.uint32)
    rows = jax.device_put(rows_np, device)
    rows128 = jax.device_put(np.zeros((b, 128), np.uint32), device)
    key = jax.device_put(rng.randint(0, 1 << 31, size=b).astype(np.uint32), device)
    vals = jax.device_put(rng.randint(0, 255, size=b).astype(np.uint32), device)
    order = jax.device_put(rng.permutation(b).astype(np.int32), device)

    def timeit(fn, *xs):
        out = fn(*xs)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(args.repeats):
            out = fn(*xs)
        jax.block_until_ready(out)
        return round((time.perf_counter() - t0) / args.repeats * 1e3, 3)

    results: dict = {"platform": device.platform, "batch": b, "n_slots": n}

    results["gather_flat_u32_ms"] = timeit(jax.jit(lambda t, i: t[i]), tab1, idx)
    results["gather_row8_ms"] = timeit(jax.jit(lambda t, i: t[i]), tab8, idx)
    results["gather_row8_x4_ms"] = timeit(jax.jit(lambda t, c: t[c]), tab8, cand)
    results["gather_bucket32_ms"] = timeit(jax.jit(lambda t, i: t[i]), tab32, idx4)
    results["gather_bucket128_ms"] = timeit(
        jax.jit(lambda t, i: t[i]), tab128, idx16
    )
    results["gather_flat_x4_ms"] = timeit(jax.jit(lambda t, c: t[c]), tab1, cand)

    results["scatter_row8_ms"] = timeit(
        jax.jit(lambda t, i, r: t.at[i].set(r, mode="drop", unique_indices=True)),
        tab8,
        idx,
        rows,
    )
    results["scatter_bucket128_ms"] = timeit(
        jax.jit(
            lambda t, i, r: t.at[i].set(r, mode="drop", unique_indices=True)
        ),
        tab128,
        idx16,
        rows128,
    )
    results["scatter_flat_ms"] = timeit(
        jax.jit(lambda t, i, v: t.at[i].set(v, mode="drop", unique_indices=True)),
        tab1,
        idx,
        vals,
    )

    results["sort2_ms"] = timeit(
        jax.jit(
            lambda k: jax.lax.sort(
                (k, jnp.arange(b, dtype=jnp.int32)), num_keys=1, is_stable=True
            )
        ),
        key,
    )
    results["perm_gather_u32_ms"] = timeit(jax.jit(lambda v, o: v[o]), vals, order)
    results["perm_gather_row8_ms"] = timeit(jax.jit(lambda v, o: v[o]), rows, order)
    results["cumsum_cummax_ms"] = timeit(
        jax.jit(lambda v: (jnp.cumsum(v), jax.lax.cummax(v))), vals
    )

    print(json.dumps(results))
    print(f"[microbench] {results}", file=sys.stderr)


if __name__ == "__main__":
    main()
