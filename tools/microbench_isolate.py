"""Isolate WHICH op class makes the probe 1000x slower than its parts.

tools/microbench_varied.py showed flat-vs-shaped probe layouts are equally
slow (~110ms at b=2^20) while one flat gather is 0.03ms. Candidates:
  * computed (derived) gather indices vs raw input indices
  * gather count (2, 4, 12 independent gathers in one program)
  * bool (i1) compares / logic / where vs arithmetic int32 masks
  * select chains over gathered values
Each variant runs with varied index inputs per repeat.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=1 << 20)
    ap.add_argument("--slots", type=int, default=1 << 23)
    ap.add_argument("--repeats", type=int, default=8)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    device = jax.devices()[0]
    b, n = args.batch, args.slots
    if device.platform != "tpu" and b > (1 << 14):
        b, n = 1 << 13, 1 << 18

    rng = np.random.RandomState(0)
    idxs = [
        jax.device_put(rng.randint(0, n, size=b).astype(np.uint32), device)
        for _ in range(args.repeats)
    ]
    tab1 = jax.device_put(rng.randint(0, 1 << 31, size=n).astype(np.uint32), device)
    now = jnp.int32(1 << 30)
    mask = np.uint32(n - 1)

    def timeit(label, fn):
        f = jax.jit(fn)
        out = f(tab1, idxs[-1])
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        outs = [f(tab1, x) for x in idxs]
        jax.block_until_ready(outs)
        ms = round((time.perf_counter() - t0) / len(idxs) * 1e3, 3)
        results[label] = ms
        print(f"[isolate] {label}: {ms}ms", file=sys.stderr)

    results: dict = {"platform": device.platform, "batch": b, "n_slots": n}

    timeit("g1_raw", lambda t, i: t[i].sum())
    timeit("g1_computed", lambda t, i: t[(i * jnp.uint32(7) + 3) & mask].sum())
    timeit("g2_raw", lambda t, i: t[i].sum() + t[(i + 1) & mask].sum())
    timeit(
        "g4_computed",
        lambda t, i: sum(
            t[(i * jnp.uint32(2 * k + 1) + k) & mask].sum() for k in range(4)
        ),
    )
    timeit(
        "g12_computed",
        lambda t, i: sum(
            t[(i * jnp.uint32(2 * k + 1) + k) & mask].sum() for k in range(12)
        ),
    )
    # one gather + bool compare + bool reduce
    timeit("g1_cmp_bool", lambda t, i: (t[i].astype(jnp.int32) > now).sum())
    # same semantics, no i1 anywhere: arithmetic sign mask
    timeit(
        "g1_cmp_arith",
        lambda t, i: ((now - t[i].astype(jnp.int32)) >> 31).sum(),
    )
    # compare two gathered values (the match test shape)
    timeit("g2_eq_bool", lambda t, i: (t[i] == t[(i + 1) & mask]).sum())
    # where-select over a gathered compare
    timeit(
        "g2_where",
        lambda t, i: jnp.where(
            t[i].astype(jnp.int32) > now, i.astype(jnp.int32), -1
        ).sum(),
    )
    # compare against a NON-gathered operand (pure elementwise)
    timeit("cmp_elementwise", lambda t, i: (i.astype(jnp.int32) > now).sum())
    timeit(
        "where_elementwise",
        lambda t, i: jnp.where(
            i.astype(jnp.int32) > now, i.astype(jnp.int32), -1
        ).sum(),
    )

    print(json.dumps(results))


if __name__ == "__main__":
    main()
