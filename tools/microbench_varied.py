"""Trust-check for the gather cost model: varied inputs per repeat.

tools/microbench_gather.py repeats identical calls; if any layer caches
identical executions the numbers would be fiction. This stages R distinct
index arrays and loops over them (the real bench's pattern), timing:
  * flat u32 gather, varied idx
  * the unrolled-K flat probe select chain (the proposed redesign)
  * the current (b, K) shaped probe choose (the suspected pathology)
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=1 << 20)
    ap.add_argument("--slots", type=int, default=1 << 23)
    ap.add_argument("--repeats", type=int, default=8)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    device = jax.devices()[0]
    b, n = args.batch, args.slots
    if device.platform != "tpu" and b > (1 << 14):
        b, n = 1 << 13, 1 << 18

    rng = np.random.RandomState(0)
    R = args.repeats
    idxs = [
        jax.device_put(rng.randint(0, n, size=b).astype(np.uint32), device)
        for _ in range(R)
    ]
    tab1 = jax.device_put(
        rng.randint(0, 1 << 31, size=n).astype(np.uint32), device
    )
    tab8 = jax.device_put(
        rng.randint(0, 1 << 31, size=(n, 8)).astype(np.uint32), device
    )
    now = jnp.int32(1 << 30)

    def timeit(fn, inputs):
        out = fn(inputs[-1])
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        outs = [fn(x) for x in inputs]
        jax.block_until_ready(outs)
        return round((time.perf_counter() - t0) / len(inputs) * 1e3, 3)

    results: dict = {"platform": device.platform, "batch": b, "n_slots": n}

    gather_flat = jax.jit(lambda t, i: t[i].sum())
    results["gather_flat_varied_ms"] = timeit(lambda i: gather_flat(tab1, i), idxs)

    @jax.jit
    def probe_flat(tab1, fp_lo):
        fp_hi = fp_lo ^ jnp.uint32(0x9E3779B9)
        step = fp_hi | jnp.uint32(1)
        mask = jnp.uint32(n - 1)
        match_any = jnp.zeros(fp_lo.shape, jnp.bool_)
        avail_any = jnp.zeros(fp_lo.shape, jnp.bool_)
        match_slot = jnp.zeros(fp_lo.shape, jnp.int32)
        avail_slot = jnp.zeros(fp_lo.shape, jnp.int32)
        cand0 = None
        for k in reversed(range(4)):
            cand = ((fp_lo + jnp.uint32(k) * step) & mask).astype(jnp.int32)
            if k == 0:
                cand0 = cand
            st_lo = tab1[cand]
            st_hi = tab1[(cand + 1) & (n - 1)]
            st_exp = tab1[(cand + 2) & (n - 1)].astype(jnp.int32)
            live = st_exp > now
            match = live & (st_lo == fp_lo) & (st_hi == fp_hi)
            avail = ~live
            match_slot = jnp.where(match, cand, match_slot)
            avail_slot = jnp.where(avail, cand, avail_slot)
            match_any = match_any | match
            avail_any = avail_any | avail
        chosen = jnp.where(
            match_any, match_slot, jnp.where(avail_any, avail_slot, cand0)
        )
        return chosen.sum()

    results["probe_flat_unrolled_ms"] = timeit(lambda i: probe_flat(tab1, i), idxs)

    @jax.jit
    def probe_shaped(tab8, fp_lo):
        fp_hi = fp_lo ^ jnp.uint32(0x9E3779B9)
        step = fp_hi | jnp.uint32(1)
        mask = jnp.uint32(n - 1)
        j = jnp.arange(4, dtype=jnp.uint32)
        cand = ((fp_lo[:, None] + j[None, :] * step[:, None]) & mask).astype(
            jnp.int32
        )
        rows = tab8[cand]
        live = rows[:, :, 4].astype(jnp.int32) > now
        match = (
            live
            & (rows[:, :, 0] == fp_lo[:, None])
            & (rows[:, :, 1] == fp_hi[:, None])
        )
        avail = ~live
        match_any = match.any(axis=1)
        avail_any = avail.any(axis=1)
        pick = jnp.where(
            match_any,
            jnp.argmax(match, axis=1),
            jnp.where(avail_any, jnp.argmax(avail, axis=1), 0),
        )
        chosen = jnp.take_along_axis(cand, pick[:, None], axis=1)[:, 0]
        return chosen.sum()

    results["probe_shaped_ms"] = timeit(lambda i: probe_shaped(tab8, i), idxs)

    print(json.dumps(results))
    print(f"[varied] {results}", file=sys.stderr)


if __name__ == "__main__":
    main()
