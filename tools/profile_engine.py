"""Per-stage breakdown of one slab engine step on the attached device.

VERDICT r3 #2 asked for a recorded hardware profile of the engine hot path
before optimizing further. Rather than a TensorBoard trace (unreadable in a
JSON artifact), this times each pipeline stage as its own jitted program —
probe gather, sort, permutation gathers, the update math (Pallas and XLA
twins), scatter, unsort — plus the full fused step, so the dominant cost is
a number in the output, not a guess. Stages are timed with donated inputs
where the real step donates, a warmup call to exclude compile, and
block_until_ready around a fixed repeat count.

Usage (chip-attached host; CPU works too for smoke):

    python tools/profile_engine.py [--batch 1048576] [--slots 8388608] \
        [--repeats 8]

Prints one JSON object. Stage times overlap (the full step is NOT the sum:
XLA fuses across stage boundaries), so read them as attribution, not an
exact partition.
"""

from __future__ import annotations

import argparse
import functools
import json
import sys
import time

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=1 << 20)
    ap.add_argument("--slots", type=int, default=1 << 23)
    ap.add_argument("--keys", type=int, default=10_000_000)
    ap.add_argument("--repeats", type=int, default=8)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from api_ratelimit_tpu.ops.slab import (
        SlabBatch,
        _choose_ways,
        _slab_step_sorted,
        _slab_update_sorted,
        _unsort,
        make_slab,
    )

    device = jax.devices()[0]
    on_tpu = device.platform == "tpu"
    if not on_tpu and args.batch > (1 << 14):
        args.batch, args.slots, args.keys = 1 << 13, 1 << 18, 100_000

    rng = np.random.RandomState(0)
    ids_np = (rng.zipf(1.1, size=args.batch).astype(np.uint64) % args.keys).astype(
        np.uint32
    )
    ids = jax.device_put(ids_np, device)
    now = jnp.int32(int(time.time()))

    def fmix(x):
        x = x ^ (x >> 16)
        x = x * jnp.uint32(0x85EBCA6B)
        x = x ^ (x >> 13)
        x = x * jnp.uint32(0xC2B2AE35)
        return x ^ (x >> 16)

    def expand(ids):
        return SlabBatch(
            fp_lo=fmix(ids),
            fp_hi=fmix(ids ^ jnp.uint32(0x9E3779B9)),
            hits=jnp.ones_like(ids),
            limit=jnp.full_like(ids, 100),
            divider=jnp.full_like(ids, 1).astype(jnp.int32),
            jitter=jnp.zeros_like(ids).astype(jnp.int32),
        )

    state0 = jax.device_put(make_slab(args.slots), device)
    table0 = state0.table

    def timeit(fn, *xs, repeats=args.repeats):
        out = fn(*xs)  # warmup/compile
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(repeats):
            out = fn(*xs)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / repeats * 1e3  # ms

    results: dict = {
        "platform": device.platform,
        "batch": args.batch,
        "n_slots": args.slots,
        "repeats": args.repeats,
    }

    # --- stage: fingerprint expansion only ---
    @jax.jit
    def stage_expand(ids):
        b = expand(ids)
        return b.fp_lo, b.fp_hi

    results["expand_ms"] = round(timeit(stage_expand, ids), 3)

    # --- stage: probe (the (b, K, 8) table gather + selects) ---
    @jax.jit
    def stage_probe(table, ids):
        from api_ratelimit_tpu.ops.slab import SlabState

        return _choose_ways(SlabState(table=table), expand(ids), now, 128)

    results["probe_ms"] = round(timeit(stage_probe, table0, ids), 3)

    # --- stage: set scan + packed single-key sort (the shipped _sort_key) ---
    from api_ratelimit_tpu.ops.slab import _sort_key

    @jax.jit
    def stage_sort(table, ids):
        from api_ratelimit_tpu.ops.slab import SlabState

        batch = expand(ids)
        chosen, _cls, matched, rows = _choose_ways(
            SlabState(table=table), batch, now, 128
        )
        key = _sort_key(chosen, matched, batch.fp_hi, table.shape[0])
        b = chosen.shape[0]
        return jax.lax.sort(
            (key, jnp.arange(b, dtype=jnp.int32)), num_keys=1, is_stable=True
        )

    results["probe_plus_sort_ms"] = round(timeit(stage_sort, table0, ids), 3)

    # --- full update, XLA math, no decide (after-mode compute) ---
    @functools.partial(jax.jit, donate_argnames=("table",), static_argnames=("pallas",))
    def stage_update(table, ids, pallas):
        from api_ratelimit_tpu.ops.slab import SlabState

        state, _b, s_after, _i, order, _h, _ = _slab_update_sorted(
            SlabState(table=table), expand(ids), now, 4, use_pallas=pallas
        )
        return state.table, _unsort(s_after, order).astype(jnp.uint8)

    # donation burns the buffer each call: re-donate a fresh copy per repeat
    def timeit_donating(fn, pallas):
        tables = [jnp.array(table0) for _ in range(args.repeats + 1)]
        jax.block_until_ready(tables)
        out = fn(tables[-1], ids, pallas)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        outs = [fn(tables[i], ids, pallas) for i in range(args.repeats)]
        jax.block_until_ready(outs)
        return (time.perf_counter() - t0) / args.repeats * 1e3

    results["update_xla_ms"] = round(timeit_donating(stage_update, False), 3)
    if on_tpu:
        try:
            results["update_pallas_ms"] = round(
                timeit_donating(stage_update, True), 3
            )
        except Exception as e:
            results["update_pallas_error"] = str(e)[-200:]

    # --- full decided step (the bench headline program) ---
    @functools.partial(jax.jit, donate_argnames=("table",), static_argnames=("pallas",))
    def stage_full(table, ids, pallas):
        from api_ratelimit_tpu.ops.slab import SlabState

        state, _b, _a, d, order, _h = _slab_step_sorted(
            SlabState(table=table),
            expand(ids),
            now,
            jnp.float32(0.8),
            ways=128,
            use_pallas=pallas,
            count_health=True,
        )
        return state.table, jnp.packbits(_unsort(d.code, order) == 2)

    results["full_decided_xla_ms"] = round(timeit_donating(stage_full, False), 3)
    if on_tpu:
        try:
            results["full_decided_pallas_ms"] = round(
                timeit_donating(stage_full, True), 3
            )
        except Exception as e:
            results["full_decided_pallas_error"] = str(e)[-200:]

    per_ms = args.batch / 1e3
    best = min(
        v
        for k, v in results.items()
        if k.startswith("full_decided") and isinstance(v, (int, float))
    )
    results["implied_decisions_per_sec"] = round(per_ms / best * 1e6)
    print(json.dumps(results))
    print(
        f"[profile] batch={args.batch} best full step {best:.2f}ms -> "
        f"{results['implied_decisions_per_sec']:,} dec/s",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
