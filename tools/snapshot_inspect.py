"""Offline slab-snapshot inspector: dump headers, verify CRCs, row stats.

Operator muscle for the warm-restart subsystem (api_ratelimit_tpu/persist/):
given snapshot files written by the SlabSnapshotter, print each file's
header, verify both CRCs and the payload length, and summarize the rows —
how many slots are occupied, how many would survive the restore
reconciliation at a given clock, counter totals. Exit 1 if ANY file fails
validation, so the tool doubles as a pre-restore health gate in deploy
scripts:

    python tools/snapshot_inspect.py /var/lib/ratelimit/snapshots/*.snap
    python tools/snapshot_inspect.py --json --now 1754300000 slab.snap

No jax import — inspection must run on any box (deploy tooling, a laptop
with a copied snapshot), not just TPU hosts; the format lives in
persist/snapshot.py which is numpy + stdlib only.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from api_ratelimit_tpu.persist.snapshot import (  # noqa: E402
    ALGO_DIV_MASK,
    ALGO_NAMES,
    COL_COUNT,
    COL_DIVIDER,
    COL_EXPIRE,
    COL_WINDOW,
    row_algorithms,
    FED_COL_EXPIRE,
    FED_COL_GRANTED,
    FED_COL_OUT,
    FED_COL_SETTLED,
    FED_COL_SPENT,
    FLAG_FED,
    FLAG_LEASE_TABLE,
    FLAG_VICTIM,
    LEASE_COL_EXPIRE,
    LEASE_COL_GRANTED,
    LEASE_COL_SETTLED,
    SNAPSHOT_VERSION,
    SnapshotError,
    load_snapshot,
    reconcile_fed_shares,
    reconcile_leases,
    reconcile_rows,
    set_occupancy_histogram,
)


def inspect_file(path: str, now: int | None) -> dict:
    """Fully validate one snapshot file and return its report dict;
    raises SnapshotError on any validation failure. Lease-liability
    tables (FLAG_LEASE_TABLE — the leases.snap section) get their own
    report shape: outstanding grants, unsettled tokens, and how the
    boot-time reconcile at `now` would treat them. Federation share
    ledgers (FLAG_FED — the fed.snap section, cluster/federation.py)
    likewise: outstanding inter-cluster shares, unsettled spend, and the
    reconcile-at-`now` preview."""
    header, table = load_snapshot(path)
    at = int(now) if now is not None else int(header.created_at)
    if header.flags & FLAG_LEASE_TABLE:
        granted = table[:, LEASE_COL_GRANTED].astype(np.int64)
        settled = table[:, LEASE_COL_SETTLED].astype(np.int64)
        expire_at = table[:, LEASE_COL_EXPIRE].astype(np.int64)
        _kept, rec = reconcile_leases(table, at)
        return {
            "path": path,
            "valid": True,
            "kind": "leases",
            "version": header.version,
            "created_at": header.created_at,
            "age_seconds": max(0, at - header.created_at),
            "bytes": os.path.getsize(path),
            "leases": {
                "outstanding": int(table.shape[0]),
                "granted_tokens": int(granted.sum()),
                "settled_tokens": int(settled.sum()),
                # the Σ budgets term of the crash-overshoot bound
                "unsettled_tokens": int((granted - settled).sum()),
                "ttl_dead_at_now": int(np.sum(expire_at <= at)),
                "restorable": rec["restored"],
                "dropped_on_restore": rec["dropped"],
            },
        }
    if header.flags & FLAG_FED:
        granted = table[:, FED_COL_GRANTED].astype(np.int64)
        spent = table[:, FED_COL_SPENT].astype(np.int64)
        settled = table[:, FED_COL_SETTLED].astype(np.int64)
        out = table[:, FED_COL_OUT].astype(np.int64)
        expire_at = table[:, FED_COL_EXPIRE].astype(np.int64)
        _kept, rec = reconcile_fed_shares(table, at)
        return {
            "path": path,
            "valid": True,
            "kind": "federation",
            "version": header.version,
            "created_at": header.created_at,
            "age_seconds": max(0, at - header.created_at),
            "bytes": os.path.getsize(path),
            "shares": {
                "rows": int(table.shape[0]),
                "granted_tokens": int(granted.sum()),
                "spent_tokens": int(spent.sum()),
                "settled_tokens": int(settled.sum()),
                # the Σ outstanding-shares term of the partition
                # overshoot bound (cluster/federation.py)
                "outstanding_tokens": int(out.sum()),
                "unsettled_tokens": int(
                    np.maximum(spent - settled, 0).sum()
                ),
                "ttl_dead_at_now": int(np.sum(expire_at <= at)),
                "restorable": rec["restored"],
                "dropped_on_restore": rec["dropped"],
            },
        }
    if header.flags & FLAG_VICTIM:
        # victim-tier section (backends/victim.py — the victim.snap file):
        # demoted live slab rows in the ordinary slab row wire, so the
        # slab reconcile rules preview the restore and the divider word
        # classifies per-row algorithms. Age histogram over window
        # position: how long rows had been parked when the file was cut.
        occupied = table.any(axis=1)
        expire_at = table[:, COL_EXPIRE].astype(np.int64)
        live = occupied & (expire_at > at)
        _kept, rec = reconcile_rows(table, at)
        counts = table[:, COL_COUNT].astype(np.int64)
        algos = row_algorithms(table)
        algo_counts = {
            name: int(np.sum(occupied & (algos == aid)))
            for aid, name in ALGO_NAMES.items()
        }
        ages = np.maximum(
            0, at - table[:, COL_WINDOW].astype(np.int64)
        )[occupied]
        age_hist = {}
        prev = 0
        for bound, label in (
            (10, "<10s"),
            (60, "<60s"),
            (600, "<600s"),
            (1 << 62, ">=600s"),
        ):
            n = int(np.sum(ages < bound))
            age_hist[label] = n - prev
            prev = n
        return {
            "path": path,
            "valid": True,
            "kind": "victim",
            "version": header.version,
            "created_at": header.created_at,
            "age_seconds": max(0, at - header.created_at),
            "bytes": os.path.getsize(path),
            "algorithms": algo_counts,
            "rows": {
                "occupied": int(np.sum(occupied)),
                "live_at_now": int(np.sum(live)),
                "restorable": rec["restored"],
                "dropped_expired": rec["dropped_expired"],
                "dropped_window": rec["dropped_window"],
                # Σ counts parked in the tier — the decision state the
                # tier is holding against loss
                "count_sum": (
                    int(counts[occupied].sum()) if occupied.any() else 0
                ),
                "age_histogram": age_hist,
            },
        }
    occupied = table.any(axis=1)
    expire_at = table[:, COL_EXPIRE].astype(np.int64)
    live = occupied & (expire_at > at)
    _reconciled, rec = reconcile_rows(table, at)
    counts = table[:, COL_COUNT].astype(np.int64)
    # per-set occupancy: v2 headers carry the writer's ways; v1 files are
    # open-addressed, so the set view only applies post-migration — show
    # the histogram at the default geometry with a migration note instead
    ways = header.ways or 0
    set_view = None
    if ways and header.n_slots % ways == 0:
        hist = set_occupancy_histogram(table, ways)
        nonzero = {
            int(k): int(v) for k, v in enumerate(hist) if v
        }
        full_sets = int(hist[ways]) if hist.shape[0] > ways else 0
        set_view = {
            "ways": ways,
            "n_sets": header.n_slots // ways,
            "occupancy_histogram": nonzero,
            "full_sets": full_sets,
            "max_set_occupancy": max(nonzero) if nonzero else 0,
        }
    # per-row algorithm class (divider word bits 28-30; pre-algorithm
    # files carry 0 everywhere => all rows classify fixed_window)
    algos = row_algorithms(table)
    algo_counts = {
        name: int(np.sum(occupied & (algos == aid)))
        for aid, name in ALGO_NAMES.items()
    }
    report = {
        "path": path,
        "valid": True,
        "kind": "slab",
        "algorithms": algo_counts,
        "version": header.version,
        "needs_migration": header.version < SNAPSHOT_VERSION,
        "sets": set_view,
        "created_at": header.created_at,
        "age_seconds": max(0, at - header.created_at),
        "shard": f"{header.shard_index}/{header.shard_count}",
        "n_slots": header.n_slots,
        "row_width": header.row_width,
        # cluster keyspace stamp (FLAG_PARTITION; cluster/): which
        # partition and route-set range this file's owner served
        "partition": (
            {
                "index": header.partition[0],
                "range": [header.partition[1], header.partition[2]],
                "route_sets": header.partition[3],
            }
            if header.partition is not None
            else None
        ),
        "bytes": os.path.getsize(path),
        "rows": {
            "occupied": int(np.sum(occupied)),
            "live_at_now": int(np.sum(live)),
            "restorable": rec["restored"],
            "dropped_expired": rec["dropped_expired"],
            "dropped_window": rec["dropped_window"],
            "count_sum": int(counts[occupied].sum()) if occupied.any() else 0,
            "count_max": int(counts[occupied].max()) if occupied.any() else 0,
            "dividers": sorted(
                int(d)
                for d in np.unique(
                    table[occupied, COL_DIVIDER] & np.uint32(ALGO_DIV_MASK)
                )
            )
            if occupied.any()
            else [],
            "window_span_s": (
                int(
                    table[occupied, COL_WINDOW].astype(np.int64).max()
                    - table[occupied, COL_WINDOW].astype(np.int64).min()
                )
                if occupied.any()
                else 0
            ),
        },
    }
    return report


def _print_text(report: dict) -> None:
    if report.get("kind") == "leases":
        leases = report["leases"]
        print(f"{report['path']}:")
        print(
            f"  header  v{report['version']} lease-liability table "
            f"created_at={report['created_at']} "
            f"(age {report['age_seconds']}s) "
            f"({report['bytes']} bytes)  CRC OK"
        )
        print(
            f"  leases  outstanding={leases['outstanding']} "
            f"unsettled_tokens={leases['unsettled_tokens']} "
            f"(granted={leases['granted_tokens']}, "
            f"settled={leases['settled_tokens']})"
        )
        print(
            f"  restore restorable={leases['restorable']} "
            f"dropped={leases['dropped_on_restore']} "
            f"ttl_dead={leases['ttl_dead_at_now']}"
        )
        return
    if report.get("kind") == "federation":
        shares = report["shares"]
        print(f"{report['path']}:")
        print(
            f"  header  v{report['version']} federation share ledger "
            f"created_at={report['created_at']} "
            f"(age {report['age_seconds']}s) "
            f"({report['bytes']} bytes)  CRC OK"
        )
        print(
            f"  shares  rows={shares['rows']} "
            f"outstanding_tokens={shares['outstanding_tokens']} "
            f"unsettled_tokens={shares['unsettled_tokens']} "
            f"(granted={shares['granted_tokens']}, "
            f"spent={shares['spent_tokens']}, "
            f"settled={shares['settled_tokens']})"
        )
        print(
            f"  restore restorable={shares['restorable']} "
            f"dropped={shares['dropped_on_restore']} "
            f"ttl_dead={shares['ttl_dead_at_now']}"
        )
        return
    if report.get("kind") == "victim":
        rows = report["rows"]
        print(f"{report['path']}:")
        print(
            f"  header  v{report['version']} victim tier "
            f"created_at={report['created_at']} "
            f"(age {report['age_seconds']}s) "
            f"({report['bytes']} bytes)  CRC OK"
        )
        print(
            f"  rows    occupied={rows['occupied']} "
            f"live={rows['live_at_now']} "
            f"restorable={rows['restorable']} "
            f"dropped(expired={rows['dropped_expired']}, "
            f"window_ended={rows['dropped_window']}) "
            f"count_sum={rows['count_sum']}"
        )
        algos = report.get("algorithms")
        if algos:
            body = " ".join(f"{k}:{v}" for k, v in algos.items() if v)
            print(f"  algos   {body or 'fixed_window:0 (empty)'}")
        ages = " ".join(
            f"{k}:{v}" for k, v in rows["age_histogram"].items()
        )
        print(f"  ages    {ages}")
        return
    rows = report["rows"]
    print(f"{report['path']}:")
    print(
        f"  header  v{report['version']} shard {report['shard']} "
        f"created_at={report['created_at']} "
        f"(age {report['age_seconds']}s) "
        f"{report['n_slots']} slots x {report['row_width']} words "
        f"({report['bytes']} bytes)  CRC OK"
    )
    print(
        f"  rows    occupied={rows['occupied']} live={rows['live_at_now']} "
        f"restorable={rows['restorable']} "
        f"dropped(expired={rows['dropped_expired']}, "
        f"window_ended={rows['dropped_window']})"
    )
    part = report.get("partition")
    if part:
        print(
            f"  cluster partition {part['index']} owning route sets "
            f"[{part['range'][0]}, {part['range'][1]}) of "
            f"{part['route_sets']}"
        )
    print(
        f"  counts  sum={rows['count_sum']} max={rows['count_max']} "
        f"dividers={rows['dividers']} window_span={rows['window_span_s']}s"
    )
    algos = report.get("algorithms")
    if algos:
        body = " ".join(f"{k}:{v}" for k, v in algos.items() if v)
        print(f"  algos   {body or 'fixed_window:0 (empty)'}")
    if report.get("needs_migration"):
        print(
            f"  layout  v{report['version']} open-addressed — boot will "
            f"rehash rows into the running set geometry (migration path)"
        )
    sets = report.get("sets")
    if sets:
        hist = sets["occupancy_histogram"]
        # render a compact k:count line, capped to the busiest entries
        top = sorted(hist.items())[-8:]
        body = " ".join(f"{k}:{v}" for k, v in top)
        print(
            f"  sets    {sets['n_sets']} x {sets['ways']}-way; "
            f"occupancy histogram (rows/set: sets) {body}; "
            f"full={sets['full_sets']} max={sets['max_set_occupancy']}"
        )


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="Dump and verify slab snapshot files offline."
    )
    parser.add_argument("files", nargs="+", help="snapshot file(s)")
    parser.add_argument(
        "--json", action="store_true", help="emit one JSON array of reports"
    )
    parser.add_argument(
        "--now",
        type=int,
        default=None,
        help="clock (unix s) for liveness/reconcile stats; default: each "
        "file's created_at (set this to time.time() to preview a restore "
        "happening now)",
    )
    parser.add_argument(
        "--wallclock",
        action="store_true",
        help="shorthand for --now=<current unix time>",
    )
    args = parser.parse_args(argv)
    now = int(time.time()) if args.wallclock else args.now

    reports: list[dict] = []
    failed = 0
    for path in args.files:
        try:
            reports.append(inspect_file(path, now))
        except SnapshotError as e:
            failed += 1
            reports.append({"path": path, "valid": False, "error": str(e)})
    if args.json:
        print(json.dumps(reports, indent=2))
    else:
        for report in reports:
            if report["valid"]:
                _print_text(report)
            else:
                print(f"{report['path']}: INVALID — {report['error']}")
    if failed:
        print(
            f"snapshot-inspect: {failed} of {len(args.files)} file(s) "
            f"failed validation",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
